"""Deterministic fault scheduling + per-layer injectors.

A :class:`ChaosPlan` is a set of named, non-overlapping-in-name
:class:`FaultWindow` intervals on the caller's clock (the drills' virtual
clock — the plan never reads time itself; callers pass ``now``). The drill
loop calls :meth:`ChaosPlan.poll` once per tick and receives the
``("begin"|"end", window)`` transitions that became due, applying each
window's bound injector — so an identical seed replays the identical fault
timeline bit-for-bit.

Injectors are small explicit objects wrapping one layer's REAL failure
seam — nothing here monkeypatches a hot path:

- :class:`BrokerReplicaOutage` — stops a netbroker replica so the primary's
  next produce shrinks the ISR below ``min_isr`` and fails with
  ``NotEnoughReplicasError`` (records land above the high watermark,
  invisible); ``end`` starts a fresh replica and ``add_replica``'s backlog
  sync re-replicates and re-exposes the tail.
- :class:`ConsumerMemberKill` — expires a consumer-group member's session
  on the fake Kafka coordinator (process death without LeaveGroup), forcing
  a rebalance onto the survivors.
- :class:`DeviceReplicaDeath` — arms ``DevicePool.inject_fault`` so the
  replica's next result fetches raise mid-flight (the retry-on-healthy-
  replica path); ``end`` revives it into the rotation.
- :class:`SlowDevice` — arms ``DevicePool.inject_slow``: a delayed device,
  not a dead one (FIFO completion must hold while one replica lags).
- :class:`LabelStall` — a gate the drill's label-release loop consults;
  while active the label stream is withheld (the feedback join's
  out-of-order/watermark discipline absorbs the burst on release).

The plan keeps a bounded event ledger and a snapshot shaped for
``MetricsCollector.sync_chaos`` (the ``chaos_*`` Prometheus series).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "FaultWindow",
    "ChaosPlan",
    "BrokerReplicaOutage",
    "ConsumerMemberKill",
    "DeviceReplicaDeath",
    "SlowDevice",
    "LabelStall",
    "WorkerKill",
]


@dataclasses.dataclass(frozen=True)
class FaultWindow:
    """One scheduled fault: ``[t_start, t_end)`` on the caller's clock."""

    name: str            # unique within a plan ("broker_outage", ...)
    kind: str            # injector family (for reporting/metrics labels)
    t_start: float
    t_end: float

    def validate(self) -> None:
        if not self.name or not self.kind:
            raise ValueError("fault window needs a name and a kind")
        if not self.t_end > self.t_start:
            raise ValueError(
                f"fault window {self.name!r} needs t_end > t_start, got "
                f"[{self.t_start}, {self.t_end})")


class ChaosPlan:
    """Fault timeline + injector binding + transition ledger."""

    def __init__(self, windows: List[FaultWindow]):
        names = [w.name for w in windows]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate fault window names in {names}")
        for w in windows:
            w.validate()
        self.windows = sorted(windows, key=lambda w: (w.t_start, w.name))
        self._injectors: Dict[str, Any] = {}
        self._begun: set = set()
        self._ended: set = set()
        self.events: List[Dict[str, Any]] = []
        # recovery bookkeeping: window name -> virtual seconds from the
        # window's end to the plane's observed recovery (the drill records
        # it via note_recovered; sync_chaos exposes it as a gauge)
        self.recovery_s: Dict[str, float] = {}

    def bind(self, name: str, injector: Any) -> None:
        """Attach an injector (an object with ``begin(now)``/``end(now)``)
        to a scheduled window. Unbound windows are annotation-only (e.g.
        flash_crowd, whose 'injection' is the arrival schedule itself)."""
        if name not in {w.name for w in self.windows}:
            raise ValueError(f"no fault window named {name!r}")
        self._injectors[name] = injector

    # ---------------------------------------------------------------- state
    def active(self, now: float) -> List[str]:
        """Names of windows covering ``now``, in schedule order."""
        return [w.name for w in self.windows
                if w.t_start <= now < w.t_end]

    def is_active(self, name: str, now: float) -> bool:
        return name in self.active(now)

    # ----------------------------------------------------------- transitions
    def poll(self, now: float) -> List[Tuple[str, FaultWindow]]:
        """Apply every transition due at ``now``; returns them in order.
        ``begin`` fires once when ``now`` reaches ``t_start``; ``end``
        once when it reaches ``t_end`` (a window fully in the past fires
        both, in order — the plan never skips an injector's cleanup)."""
        transitions: List[Tuple[str, FaultWindow]] = []
        for w in self.windows:
            if w.name not in self._begun and now >= w.t_start:
                self._begun.add(w.name)
                transitions.append(("begin", w))
                inj = self._injectors.get(w.name)
                if inj is not None:
                    inj.begin(now)
                self.events.append({"event": "begin", "fault": w.name,
                                    "kind": w.kind, "ts": now})
            if w.name not in self._ended and now >= w.t_end:
                self._ended.add(w.name)
                transitions.append(("end", w))
                inj = self._injectors.get(w.name)
                if inj is not None:
                    inj.end(now)
                self.events.append({"event": "end", "fault": w.name,
                                    "kind": w.kind, "ts": now})
        return transitions

    def note_recovered(self, name: str, now: float) -> None:
        """Record the plane-recovery instant for an ended window (idempotent
        — the first observation wins; recovery is measured from t_end)."""
        w = next((w for w in self.windows if w.name == name), None)
        if w is None or name in self.recovery_s:
            return
        self.recovery_s[name] = max(0.0, now - w.t_end)

    # -------------------------------------------------------------- snapshot
    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        """JSON-able state for the drill summary and ``sync_chaos``."""
        return {
            "windows": [{
                "fault": w.name, "kind": w.kind,
                "t_start": w.t_start, "t_end": w.t_end,
                "begun": w.name in self._begun,
                "ended": w.name in self._ended,
                "active": (now is not None
                           and w.t_start <= now < w.t_end),
            } for w in self.windows],
            "events": list(self.events),
            "recovery_s": {k: round(v, 4)
                           for k, v in sorted(self.recovery_s.items())},
        }


# ---------------------------------------------------------------------------
# injectors
# ---------------------------------------------------------------------------


class BrokerReplicaOutage:
    """Kill a netbroker replica; restore by attaching a fresh one.

    ``replica_factory`` returns a started, read-only ``BrokerServer``
    (role="replica"); on ``end`` the primary's ``add_replica`` backlog
    sync catches the newcomer up and — once the ISR is back at
    ``min_isr`` — re-exposes any tail produced (unacked) during the
    outage. The produce failures in between are the REAL
    ``NotEnoughReplicasError`` path, not a simulation of it.
    """

    def __init__(self, primary, replica,
                 replica_factory: Callable[[], Any]):
        self.primary = primary
        self.replica = replica
        self.replica_factory = replica_factory
        self.restored_replica = None
        self.outages = 0

    def begin(self, now: float) -> None:
        self.outages += 1
        self.replica.stop()

    def end(self, now: float) -> None:
        self.restored_replica = self.replica_factory()
        self.primary.add_replica("127.0.0.1", self.restored_replica.port)


class ConsumerMemberKill:
    """Expire one consumer-group member's session on the fake Kafka
    coordinator — process death without a LeaveGroup. One-shot: ``end``
    is a no-op (the group heals by rebalancing, not by resurrection)."""

    def __init__(self, server, group_id: str, member_id: str):
        self.server = server
        self.group_id = group_id
        self.member_id = member_id
        self.killed = 0

    def begin(self, now: float) -> None:
        self.server.kill_member(self.group_id, self.member_id)
        self.killed += 1

    def end(self, now: float) -> None:
        return None


class DeviceReplicaDeath:
    """Arm a pool replica to fail its next ``n_faults`` result fetches
    mid-flight (the rescue-onto-healthy-replica path); revive on end."""

    def __init__(self, pool, replica_idx: int, n_faults: int = 1):
        self.pool = pool
        self.replica_idx = int(replica_idx)
        self.n_faults = max(1, int(n_faults))

    def begin(self, now: float) -> None:
        self.pool.inject_fault(self.replica_idx, self.n_faults)

    def end(self, now: float) -> None:
        self.pool.revive(self.replica_idx)


class SlowDevice:
    """Arm a pool replica to DELAY its next ``n`` result fetches — the
    degraded-but-alive failure mode (no retry, no health change; FIFO
    completion across the pool is the property under test)."""

    def __init__(self, pool, replica_idx: int, delay_s: float, n: int = 1):
        self.pool = pool
        self.replica_idx = int(replica_idx)
        self.delay_s = float(delay_s)
        self.n = max(1, int(n))

    def begin(self, now: float) -> None:
        self.pool.inject_slow(self.replica_idx, self.delay_s, self.n)

    def end(self, now: float) -> None:
        return None


class WorkerKill:
    """Kill a partition-parallel fleet worker with process-death
    semantics: live state and in-flight batches are gone, no graceful
    flush — the fleet's checkpointed-handoff path (snapshot restore +
    committed-gap state replay on the survivors) is what recovers.
    One-shot like :class:`ConsumerMemberKill`: ``end`` is a no-op; the
    fleet heals by rebalancing, not by resurrection.

    ``target`` is anything with ``kill_worker(worker_id, now=...)``:

    - ``cluster.fleet.WorkerFleet`` — the in-process fleet (shard-drill):
      a SIMULATED death (the thread's state is dropped cooperatively);
    - ``cluster.procfleet.ProcessFleet`` — the ESCALATED form the
      elastic drill uses: ``kill_worker`` sends a real ``SIGKILL`` to the
      worker's OS process, so the fault is delivered by the kernel, not
      by this injector's goodwill. ``worker_id="busiest"`` resolves at
      kill time to the worker owning the most partitions (deterministic
      tie-break) — the kill must move real state, not hit an idle
      member;
    - or a stub in tests.

    ``last_result`` keeps the target's kill report (returncode, replay
    depth) for the drill's verdict."""

    def __init__(self, target: Any, worker_id: str):
        self.target = target
        self.worker_id = worker_id
        self.killed = 0
        self.last_result: Optional[Dict[str, Any]] = None

    def begin(self, now: float) -> None:
        self.last_result = self.target.kill_worker(self.worker_id, now=now)
        self.killed += 1

    def end(self, now: float) -> None:
        return None


class LabelStall:
    """Gate the label stream: while active, the drill's label-release loop
    withholds due labels; on end they flood in as one out-of-order burst
    (the label join's watermark discipline must absorb it)."""

    def __init__(self) -> None:
        self.active = False
        self.stalls = 0

    def begin(self, now: float) -> None:
        self.active = True
        self.stalls += 1

    def end(self, now: float) -> None:
        self.active = False

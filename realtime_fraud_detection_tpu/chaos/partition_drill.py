"""Split-brain partition drill: prove the network fault plane end to end.

``rtfd partition-drill`` is the acceptance artifact for ISSUE 13 — the
tenth lockwatch drill. One seeded timeline drives ≥ 4 REAL OS worker
processes (``rtfd cluster-worker`` over the TCP netbroker, the PR 12
process fleet) while the link-fault layer (chaos/netfaults.py) degrades
the network they live on:

1. **asymmetric partition** at the initially-busiest worker: its
   control-plane traffic (``cluster-control`` fetches, ``cluster-events``
   produces — hellos, heartbeats, acks) is severed while its DATA path
   still reaches the broker. The coordinator's session expiry evicts it,
   fences its partitions (handoff epoch + broker producer generation),
   and reassigns them — while the deaf worker keeps scoring and
   producing. Its stamped produces bounce off the broker's generation
   fence (``StaleGenerationError``, counted): the zombie writer is
   stopped at the WRITE seam, not by luck. When the window heals, its
   hello gets through and it rejoins as a fresh member.
2. **slow link under load** at a second worker: per-frame latency (+
   seeded jitter) on every broker op — scored-traffic p99 inside the
   window vs the same worker's healthy p99 is the drill's
   ``degraded_network`` report (and the bench stage of the same name).
3. **full partition that heals** at a third worker: every broker op
   fails; the worker errors into its bounded ``DeterministicBackoff``
   loop (never crashes, never wedges — the socket-deadline hardening),
   gets evicted, and on heal discovers it was fenced (stale generation /
   fenced epoch), abandons without checkpointing, and rejoins fresh.

Checked contract (all enforced, fast AND full): real distinct processes;
the zombie's post-fence produces refused AND counted (nonzero); zero
lost and zero conflicting-scored transactions vs a single-process
oracle; gap-free committed offsets; per-key order on first emission;
state digest-equal to the oracle; both evicted workers reassigned within
the detection bound (session timeout + slack); both rejoin as fresh
members with no double-ownership interval (fenced abandon evidence +
zero conflicting emissions); scored duplicates bounded and
byte-identical; and a second fully fresh run producing the same sha256
digest over the content invariants (wall-timing fields reported, never
digested — same policy as ``rtfd elastic-drill``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from realtime_fraud_detection_tpu.chaos.faults import ChaosPlan, FaultWindow
from realtime_fraud_detection_tpu.cluster.hashring import HashRing
from realtime_fraud_detection_tpu.cluster.procfleet import (
    CONTROL_TOPIC,
    DIGEST_NOW,
    EVENTS_TOPIC,
    ProcessFleet,
)
from realtime_fraud_detection_tpu.stream import topics as T

__all__ = ["PartitionDrillConfig", "run_partition_drill",
           "compact_partition_summary", "build_partition_schedule",
           "drill_targets"]


def _wall() -> float:
    # rtfd-lint: allow[wall-clock] real OS processes over real TCP are paced on the wall clock by definition
    return time.time()


@dataclasses.dataclass
class PartitionDrillConfig:
    """Drill sizes. Defaults = the full drill; ``fast()`` = the tier-1
    smoke — same fleet shape (≥ 4 processes, all three fault windows,
    both rejoins), compressed timeline."""

    seed: int = 7
    n_partitions: int = 12          # the transactions topic's contract
    n_workers: int = 5
    num_users: int = 400_000
    num_merchants: int = 1_200
    hot_users: int = 3_000
    hot_frac: float = 0.35
    # offered load: constant-rate seeded Poisson arrivals
    duration_s: float = 24.0
    tps: float = 420.0
    # fault windows, relative to the announced epoch (window t=0).
    # Sequential by design: each fault's recovery must settle before the
    # next one opens, or a rejoin rebalance could wait on a partitioned
    # releaser's ack.
    asym_start: float = 4.0
    asym_end: float = 9.5
    slow_start: float = 11.5
    slow_end: float = 15.0
    slow_latency_s: float = 0.035
    slow_jitter_s: float = 0.01
    full_start: float = 17.0
    full_end: float = 21.0
    # liveness: the drill compresses the session timeout so detection
    # fits the timeline (production default is 30 s)
    session_timeout_s: float = 2.5
    heartbeat_s: float = 0.4
    detection_slack_s: float = 10.0
    # worker knobs (wall-time service-cost model, paid for real)
    batch: int = 64
    max_delay_ms: float = 20.0
    checkpoint_every: int = 5
    base_ms: float = 8.0
    per_txn_ms: float = 1.6
    reconnect_attempts: int = 2     # link faults burn client retries fast
    ack_timeout_s: float = 120.0
    drain_timeout_s: float = 180.0
    # scored-duplicate bound: an evicted worker's produce-then-refused-
    # commit window plus reconnect-epoch re-polls, never a flood
    dup_bound_abs: int = 256
    dup_bound_frac: float = 0.05
    # second, fully fresh run compared digest-for-digest with the first
    replay_check: bool = True

    @classmethod
    def fast(cls) -> "PartitionDrillConfig":
        """Tier-1 smoke: every window, both rejoins, ≥ 4 processes;
        timeline and id space shrink."""
        return cls(n_workers=4, num_users=60_000, num_merchants=400,
                   hot_users=1_200, duration_s=15.0, tps=180.0,
                   asym_start=2.5, asym_end=6.5,
                   slow_start=7.5, slow_end=10.0,
                   full_start=11.0, full_end=13.5,
                   session_timeout_s=2.0, heartbeat_s=0.35,
                   base_ms=7.0, per_txn_ms=2.2, checkpoint_every=4)

    def validate(self) -> None:
        if self.n_workers < 4:
            raise ValueError("partition drill needs >= 4 workers "
                             "(three distinct fault targets + survivors)")
        spans = [(self.asym_start, self.asym_end),
                 (self.slow_start, self.slow_end),
                 (self.full_start, self.full_end)]
        for s, e in spans:
            if not e > s >= 0:
                raise ValueError(f"bad fault window [{s}, {e})")
        for (_, e1), (s2, _) in zip(spans, spans[1:]):
            if s2 < e1:
                raise ValueError(
                    "fault windows must be sequential (a rejoin "
                    "rebalance must never wait on a partitioned "
                    "releaser)")

    def windows(self) -> List[FaultWindow]:
        return [
            FaultWindow("asym_partition", "netfault",
                        self.asym_start, self.asym_end),
            FaultWindow("slow_link", "netfault",
                        self.slow_start, self.slow_end),
            FaultWindow("full_partition", "netfault",
                        self.full_start, self.full_end),
        ]


def drill_targets(cfg: PartitionDrillConfig) -> Dict[str, str]:
    """Deterministic fault targets from the INITIAL ring placement (a
    pure function of membership — the coordinator computes the identical
    assignment): the busiest worker takes the asymmetric partition (the
    kill must threaten real state), the next two distinct workers take
    the slow link and the full partition."""
    ids = [f"w{i}" for i in range(cfg.n_workers)]
    assign = HashRing(ids).assignment(cfg.n_partitions)
    by_load = sorted(ids, key=lambda w: (len(assign.get(w, ())), w),
                     reverse=True)
    return {"zombie": by_load[0], "slow": by_load[1],
            "full": by_load[2]}


# ------------------------------------------------------------- the stream


def build_partition_schedule(cfg: PartitionDrillConfig,
                             ) -> List[Tuple[float, Dict[str, Any]]]:
    """Seeded (event_ts, txn) timeline: constant-rate Poisson arrivals
    joined to a synthetic stream (hot repeat-customer cohort + uniform
    long tail), schema-complete for ``sanitize_for_stream``."""
    rng = np.random.default_rng(cfg.seed)
    n_est = int(cfg.tps * cfg.duration_s * 1.3) + 64
    gaps = rng.exponential(1.0 / cfg.tps, size=n_est)
    times = np.cumsum(gaps)
    times = times[times < cfg.duration_s]
    n = len(times)
    hot_pool = rng.integers(0, cfg.num_users, size=max(1, cfg.hot_users))
    take_hot = rng.random(n) < cfg.hot_frac
    uid_idx = np.where(
        take_hot,
        hot_pool[rng.integers(0, len(hot_pool), size=n)],
        rng.integers(0, cfg.num_users, size=n))
    mid_idx = rng.integers(0, cfg.num_merchants, size=n)
    amounts = np.round(rng.lognormal(3.2, 0.9, size=n), 2)
    sched: List[Tuple[float, Dict[str, Any]]] = []
    for i in range(n):
        t = round(float(times[i]), 9)
        sched.append((t, {
            "transaction_id": f"ptx_{i}",
            "user_id": f"user_{int(uid_idx[i])}",
            "merchant_id": f"m_{int(mid_idx[i])}",
            "amount": float(amounts[i]),
            "payment_method": "card",
            "event_ts": t,
        }))
    return sched


# ---------------------------------------------------------------- oracle


def run_partition_oracle(cfg: PartitionDrillConfig,
                         sched: List[Tuple[float, Dict[str, Any]]],
                         ) -> Dict[str, Any]:
    """Single-process oracle: each partition's records applied in offset
    (== schedule) order through the same state-coupled scorer the
    workers run — the truth any correct fleet must land on regardless of
    partitions, evictions, fencing, or rejoins."""
    from realtime_fraud_detection_tpu.cluster.drill import ShardScorer
    from realtime_fraud_detection_tpu.cluster.partition import (
        PartitionedStore,
    )

    store = PartitionedStore(
        cfg.n_partitions, seq_len=4, feature_dim=4,
        cache_kwargs={"txn_ttl_s": 1e12, "features_ttl_s": 1e12})
    for p in range(cfg.n_partitions):
        store.acquire(p)
    scorer = ShardScorer(store)
    scores: Dict[str, Tuple[float, str]] = {}
    for _, txn in sched:
        res = scorer._score_and_update(txn)
        scores[res["transaction_id"]] = (res["fraud_score"],
                                         res["decision"])
    return {
        "scores": scores,
        "digests": {p: d for p, d in store.digests(now=DIGEST_NOW).items()},
    }


# ------------------------------------------------------------- fleet run


def _worker_netfault_specs(cfg: PartitionDrillConfig,
                           targets: Dict[str, str],
                           ) -> Dict[str, Dict[str, Any]]:
    """Per-worker spec overlays: each fault target carries exactly its
    own scheduled link windows (JSON-able — they ride the worker spec
    across the process boundary)."""
    ctl_match = {"topics": [CONTROL_TOPIC, EVENTS_TOPIC]}
    return {
        targets["zombie"]: {"netfaults": {"seed": cfg.seed, "windows": [{
            "name": "asym_partition", "kind": "partition",
            "t_start": cfg.asym_start, "t_end": cfg.asym_end,
            "mode": "full", "match": ctl_match,
        }]}},
        targets["slow"]: {
            "netfaults": {"seed": cfg.seed, "windows": [{
                "name": "slow_link", "kind": "degrade",
                "t_start": cfg.slow_start, "t_end": cfg.slow_end,
                "latency_s": cfg.slow_latency_s,
                "jitter_s": cfg.slow_jitter_s,
            }]},
            "phase_windows": {"slow_link": [cfg.slow_start, cfg.slow_end]},
        },
        targets["full"]: {"netfaults": {"seed": cfg.seed, "windows": [{
            "name": "full_partition", "kind": "partition",
            "t_start": cfg.full_start, "t_end": cfg.full_end,
            "mode": "full",
        }]}},
    }


def _run_partition_fleet(cfg: PartitionDrillConfig,
                         sched: List[Tuple[float, Dict[str, Any]]],
                         ) -> Dict[str, Any]:
    """One fresh fleet run over the schedule: own broker server, own
    handoff server + blob dir, own worker processes, own fault windows.
    """
    from realtime_fraud_detection_tpu.cluster.handoff import HandoffServer
    from realtime_fraud_detection_tpu.stream.netbroker import BrokerServer

    targets = drill_targets(cfg)
    broker_srv = BrokerServer(port=0).start()
    tmp = tempfile.mkdtemp(prefix="rtfd-partition-")
    handoff_srv = None
    fleet = None
    try:
        handoff_srv = HandoffServer(
            blob_dir=os.path.join(tmp, "blobs")).start()
        fleet = ProcessFleet(
            f"127.0.0.1:{broker_srv.port}",
            f"127.0.0.1:{handoff_srv.port}",
            n_partitions=cfg.n_partitions,
            ack_timeout_s=cfg.ack_timeout_s,
            session_timeout_s=cfg.session_timeout_s,
            spawn_env={**os.environ, "JAX_PLATFORMS": "cpu"},
            worker_spec={
                "batch": cfg.batch, "max_delay_ms": cfg.max_delay_ms,
                "checkpoint_every": cfg.checkpoint_every,
                "seq_len": 4, "feature_dim": 4,
                "base_ms": cfg.base_ms, "per_txn_ms": cfg.per_txn_ms,
                "heartbeat_s": cfg.heartbeat_s,
                "reconnect_attempts": cfg.reconnect_attempts,
            },
            per_worker_spec=_worker_netfault_specs(cfg, targets))
        fleet.start(cfg.n_workers, now=0.0)

        # coordinator-side window ledger (annotation-only: the real
        # injections run INSIDE the target workers' clients, on the same
        # windows anchored to the same epoch)
        plan = ChaosPlan(cfg.windows())

        t0 = _wall()
        fleet.announce_epoch(t0)
        next_i, n = 0, len(sched)
        produced = 0
        while True:
            now_ev = _wall() - t0
            if next_i < n:
                j = next_i
                items = []
                while j < n and sched[j][0] <= now_ev:
                    t_ev, txn = sched[j]
                    items.append((txn["user_id"], txn, t0 + t_ev))
                    j += 1
                if items:
                    fleet.client.produce_batch_stamped(T.TRANSACTIONS,
                                                       items)
                    produced += len(items)
                    next_i = j
            plan.poll(now_ev)
            fleet.tick(now_ev)
            if next_i >= n and now_ev > cfg.full_end:
                lag = fleet.client.lag(fleet.group_id, T.TRANSACTIONS)
                healed = (fleet.rejoins >= 2
                          and not fleet._pending_rejoins
                          and len(fleet.ready_ids()) == cfg.n_workers)
                if lag == 0 and healed:
                    break
                if now_ev > cfg.duration_s + cfg.drain_timeout_s:
                    raise RuntimeError(
                        f"drain timeout: lag={lag} "
                        f"rejoins={fleet.rejoins} "
                        f"ready={len(fleet.ready_ids())}")
            time.sleep(0.01)
        makespan = _wall() - t0

        broker_status = fleet.client.status()
        fleet.shutdown_all(now=_wall() - t0)
        byes = fleet.all_byes()
        digests: Dict[int, str] = {}
        counters = {"scored": 0, "duplicates_skipped": 0, "errors": 0,
                    "batches": 0}
        for wid, bye in sorted(byes.items()):
            for p, d in (bye.get("digests") or {}).items():
                digests[int(p)] = d
            for k in counters:
                counters[k] += int((bye.get("counters") or {}).get(k, 0))

        # ---- predictions ledger: one pass (coverage + agreement +
        # first-emission per-key order), the elastic-drill discipline ----
        inner = broker_srv.broker
        preds: Dict[str, List[Tuple[float, str, str]]] = {}
        order_ok = True
        last_seq: Dict[Tuple[int, str], int] = {}
        emissions = 0
        for p in range(inner.partitions(T.PREDICTIONS)):
            off = 0
            while True:
                recs = inner.read(T.PREDICTIONS, p, off, 4096)
                if not recs:
                    break
                off = recs[-1].offset + 1
                for r in recs:
                    v = r.value if isinstance(r.value, dict) else {}
                    ex = v.get("explanation") or {}
                    kind = ("replayed" if ex.get("replayed_from_cache")
                            else "error" if ex.get("error") else "scored")
                    tid = str(v.get("transaction_id", ""))
                    emissions += 1
                    first = tid not in preds
                    preds.setdefault(tid, []).append(
                        (round(float(v.get("fraud_score", -1.0)), 6),
                         str(v.get("decision", "")), kind))
                    if first:
                        uid = str(r.key or "")
                        try:
                            seq = int(tid.rsplit("_", 1)[-1])
                        except ValueError:
                            continue
                        keyp = (p, uid)
                        if last_seq.get(keyp, -1) >= seq:
                            order_ok = False
                        last_seq[keyp] = seq

        tx_ends = inner.end_offsets(T.TRANSACTIONS)
        committed = [inner.committed(fleet.group_id, T.TRANSACTIONS, p)
                     for p in range(len(tx_ends))]

        snap = fleet.snapshot()
        digest = hashlib.sha256(json.dumps({
            "produced": produced,
            # unique (score, decision) per transaction: duplicates
            # collapse (byte-identity is checked separately), so the
            # digest depends only on content, never on where inside the
            # windows the evictions landed
            "preds": sorted((tid, sorted({(s, d) for s, d, _ in e}))
                            for tid, e in preds.items()),
            "committed": committed,
            "state": sorted((p, d) for p, d in digests.items()),
            "windows": [[w.name, w.t_start, w.t_end]
                        for w in cfg.windows()],
        }, sort_keys=True).encode()).hexdigest()

        return {
            "targets": targets,
            "produced": produced,
            "preds": preds,
            "emissions": emissions,
            "order_ok": order_ok,
            "committed": committed,
            "tx_ends": tx_ends,
            "digests": digests,
            "counters": counters,
            "byes": {w: {k: v for k, v in b.items() if k != "digests"}
                     for w, b in byes.items()},
            "fleet": snap,
            "plan": plan.snapshot(now=makespan),
            "broker_status": broker_status,
            "handoff_stats": fleet.handoff.stats(),
            "makespan_s": round(makespan, 3),
            "digest": digest,
        }
    finally:
        if fleet is not None:
            fleet.terminate()
        if handoff_srv is not None:
            handoff_srv.stop()
        broker_srv.stop()


# ------------------------------------------------------------------ drill


def run_partition_drill(config: Optional[PartitionDrillConfig] = None,
                        fast: bool = False) -> Dict[str, Any]:
    """Run the partition drill: real process fleet under link chaos vs
    the single-process oracle, plus the fresh-run determinism check."""
    cfg = config or (PartitionDrillConfig.fast() if fast
                     else PartitionDrillConfig())
    cfg.validate()
    sched = build_partition_schedule(cfg)
    oracle = run_partition_oracle(cfg, sched)
    out = _run_partition_fleet(cfg, sched)
    targets = out["targets"]

    produced_ids = {txn["transaction_id"] for _, txn in sched}
    preds = out["preds"]
    lost = len(produced_ids - set(preds))
    conflicting = 0
    score_mismatches = 0
    scored_duplicates = 0
    for tid, emits in preds.items():
        scored = [(s, d) for s, d, kind in emits if kind == "scored"]
        if len(scored) > 1:
            scored_duplicates += len(scored) - 1
        if len(set(scored)) > 1:
            conflicting += 1
        want = oracle["scores"].get(tid)
        if scored and want is not None and any(sd != want for sd in scored):
            score_mismatches += 1
    errors = sum(1 for emits in preds.values()
                 for _, _, kind in emits if kind == "error")

    # --- eviction/rejoin accounting --------------------------------------
    events = out["fleet"]["events"]
    expired_at = {e["worker"]: e.get("t")
                  for e in events if e.get("event") == "session_expired"}
    rejoined = set()
    for e in events:
        if e.get("event") == "rebalance" \
                and str(e.get("reason", "")).startswith("rejoin:"):
            rejoined.update(str(e["reason"])[len("rejoin:"):].split("+"))
    window_start = {targets["zombie"]: cfg.asym_start,
                    targets["full"]: cfg.full_start}
    detect_bound = cfg.session_timeout_s + cfg.detection_slack_s
    detection_s = {}
    reassigned_in_bound = True
    for wid, w_start in window_start.items():
        t_exp = expired_at.get(wid)
        if t_exp is None:
            reassigned_in_bound = False
            continue
        detection_s[wid] = round(t_exp - w_start, 3)
        if not (0.0 <= t_exp - w_start <= detect_bound):
            reassigned_in_bound = False

    byes = out["byes"]
    z_bye = byes.get(targets["zombie"]) or {}
    f_bye = byes.get(targets["full"]) or {}
    s_bye = byes.get(targets["slow"]) or {}
    z_fenced = z_bye.get("fenced") or {}
    f_fenced = f_bye.get("fenced") or {}
    fenced_produces = int(out["broker_status"].get("fenced_produces", 0))
    fenced_commits = int(out["broker_status"].get("fenced_commits", 0))

    # --- degraded_network: the slow-link victim's own healthy-vs-window
    # scored-traffic latency + throughput (the bench stage's payload) -----
    phases = s_bye.get("latency_phases") or {}
    healthy = phases.get("healthy") or {}
    slow = phases.get("slow_link") or {}
    slow_span = cfg.slow_end - cfg.slow_start
    degraded_network = {
        "worker": targets["slow"],
        "injected_latency_ms": round(cfg.slow_latency_s * 1e3, 3),
        "healthy": {**healthy,
                    "tps": (round(healthy.get("n", 0)
                                  / max(out["makespan_s"] - slow_span,
                                        1e-9), 1))},
        "slow_link": {**slow,
                      "tps": round(slow.get("n", 0) / max(slow_span, 1e-9),
                                   1)},
        "p99_ratio": (round(slow["p99_ms"] / healthy["p99_ms"], 3)
                      if slow.get("p99_ms") and healthy.get("p99_ms")
                      else None),
    }

    dup_bound = cfg.dup_bound_abs + int(cfg.dup_bound_frac
                                        * out["produced"])

    replay_identical = None
    second_digest = None
    if cfg.replay_check:
        second = _run_partition_fleet(cfg, sched)
        second_digest = second["digest"]
        replay_identical = second_digest == out["digest"]

    distinct_pids = {st["pid"] for st in out["fleet"]["workers"].values()}
    checks = {
        "processes_real": (len(distinct_pids)
                           == len(out["fleet"]["workers"])
                           and os.getpid() not in distinct_pids),
        # the zombie kept producing after its partitions moved — and the
        # broker REFUSED it (counted, nonzero), both ends agreeing
        "zombie_fenced_produce": (fenced_produces >= 1
                                  and int(z_fenced.get(
                                      "stale_generation", 0)) >= 1),
        "zero_lost": lost == 0,
        "zero_conflicting_scored": conflicting == 0,
        "zero_errors": errors == 0,
        "offsets_gap_free": out["committed"] == out["tx_ends"],
        "per_key_order_preserved": out["order_ok"],
        "state_equals_oracle": out["digests"] == oracle["digests"],
        "scores_equal_oracle": score_mismatches == 0,
        "reassigned_within_bound": reassigned_in_bound,
        "both_targets_evicted": (targets["zombie"] in expired_at
                                 and targets["full"] in expired_at),
        "healed_workers_rejoined": (targets["zombie"] in rejoined
                                    and targets["full"] in rejoined
                                    and bool(z_bye.get("graceful"))
                                    and bool(f_bye.get("graceful"))),
        # no double-ownership interval: both evicted workers provably
        # ABANDONED on first fenced write (nothing they wrote after the
        # fence landed), and no transaction carries divergent emissions
        "no_double_ownership": (int(z_fenced.get("abandons", 0)) >= 1
                                and int(f_fenced.get("abandons", 0)) >= 1
                                and conflicting == 0),
        "duplicates_bounded": scored_duplicates <= dup_bound,
        "duplicates_identical": conflicting == 0,
        "slow_window_sampled": int(slow.get("n", 0)) >= 20,
    }
    if replay_identical is not None:
        checks["replay_deterministic"] = bool(replay_identical)

    summary: Dict[str, Any] = {
        "metric": "partition_drill",
        "passed": all(bool(v) for v in checks.values()),
        "checks": checks,
        "targets": targets,
        "n_workers": cfg.n_workers,
        "n_partitions": cfg.n_partitions,
        "produced": out["produced"],
        "scored": out["counters"]["scored"],
        "emissions": out["emissions"],
        "scored_duplicates": scored_duplicates,
        "duplicate_bound": dup_bound,
        "lost": lost,
        "conflicting_scored": conflicting,
        "score_mismatches": score_mismatches,
        "fenced_produces": fenced_produces,
        "fenced_commits": fenced_commits,
        "fenced_by_worker": {
            targets["zombie"]: z_fenced,
            targets["full"]: f_fenced,
        },
        "evictions": out["fleet"]["evictions"],
        "rejoins": out["fleet"]["rejoins"],
        "detection_s": detection_s,
        "detection_bound_s": detect_bound,
        "degraded_network": degraded_network,
        "handoff_server": out["handoff_stats"],
        "plan": out["plan"],
        "links": {w: b.get("link") for w, b in byes.items()
                  if b.get("link")},
        # wall-clock report (NEVER in the digest)
        "wall": {
            "makespan_s": out["makespan_s"],
            "rebalance_pauses_s": out["fleet"]["rebalance_pauses_s"],
        },
        "events": events,
        "replay_identical": replay_identical,
        "digest": out["digest"],
        "second_digest": second_digest,
    }
    return summary


def compact_partition_summary(summary: Dict[str, Any]) -> Dict[str, Any]:
    """The <2 KB final-stdout-line verdict (bench.py convention: full
    result on the preceding line, compact parseable verdict last)."""
    deg = summary.get("degraded_network") or {}
    compact = {
        "metric": "partition_drill",
        "passed": summary.get("passed"),
        "checks": {k: bool(v)
                   for k, v in (summary.get("checks") or {}).items()},
        "targets": summary.get("targets"),
        "produced": summary.get("produced"),
        "scored": summary.get("scored"),
        "lost": summary.get("lost"),
        "conflicting_scored": summary.get("conflicting_scored"),
        "scored_duplicates": summary.get("scored_duplicates"),
        "fenced_produces": summary.get("fenced_produces"),
        "fenced_commits": summary.get("fenced_commits"),
        "evictions": summary.get("evictions"),
        "rejoins": summary.get("rejoins"),
        "detection_s": summary.get("detection_s"),
        "slow_p99_ratio": deg.get("p99_ratio"),
        "makespan_s": (summary.get("wall") or {}).get("makespan_s"),
        "digest": (summary.get("digest") or "")[:16],
        "summary_of": "full result JSON on the preceding stdout line",
    }
    line = json.dumps(compact, separators=(",", ":"))
    while len(line.encode()) >= 2048:
        for victim in ("checks", "detection_s", "targets", "digest",
                       "summary_of"):
            if compact.pop(victim, None) is not None:
                break
        else:
            compact = {"metric": "partition_drill",
                       "passed": summary.get("passed")}
        line = json.dumps(compact, separators=(",", ":"))
    return compact

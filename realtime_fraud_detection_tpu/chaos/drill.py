"""Combined recovery drill: every plane, one correlated-failure timeline.

``rtfd chaos-drill`` is the chaos plane's acceptance artifact. One seeded,
virtual-clock timeline layers the faults the planes were proven against
*in isolation* — and proves they hold TOGETHER:

1. **healthy** — baseline stream through the REAL pipeline: netbroker
   primary + synchronous replica (min_isr=2) over real TCP, NetBrokerClient
   consumer, MicrobatchAssembler on the virtual clock, QoS admission +
   ladder + budget, tracer + SLO burn, DevicePool over the host platform's
   virtual devices, FeedbackPlane joining chargeback-delayed labels.
   Prequential AUC settles at the incumbent's baseline.
2. **flash crowd** — a ``sim.arrivals.DiurnalBurstProcess`` spike at a
   multiple of the (virtual) capacity: the QoS ladder engages, sheds only
   low-priority traffic, SLO burn spikes.
3. **broker outage** — the replica is stopped mid-stream: the primary's
   produces fail with the REAL ``NotEnoughReplicasError`` (records land
   above the high watermark, invisible), the drill's producer buffers and
   retries, the job's own fan-out failure takes the crash-recovery path
   (seek-to-committed + txn-cache replay). A fresh replica attaches;
   ``add_replica``'s backlog sync re-replicates and re-exposes the tail —
   effectively-once across the outage, offset-accounted.
4. **device faults** — one pool replica dies mid-flight (injected fetch
   failure → rescue-onto-healthy-replica), then a revived replica runs
   SLOW (delayed, not dead). FIFO completion and per-batch result
   integrity hold throughout.
5. **fraud ring** — ``sim.fraud_patterns.FraudRing``: a user cohort
   funnels traffic through shared merchants/devices/IPs, in-distribution
   per feature. The label stream stalls (and floods back); prequential
   AUC dips; the retrain policy fires; the gate passes a candidate that
   learned the ring signature; promotion deploys it through the pool's
   replica-by-replica swap.
6. **recovery** — the ring keeps flowing against the retrained blend: AUC
   recovers to the baseline band, the ladder returns to rung 0, SLO burn
   falls under 1, the pool is healthy and retry-free again.

Time is virtual throughout: arrivals carry virtual timestamps, the
assembler/admission/budget/tracer/feedback all read the injected clock,
and scoring advances the clock by a deterministic service-cost model
(``(base_ms + n*per_txn_ms) / speedup[rung]`` — the ladder's rungs
genuinely buy virtual capacity). The REAL parts — TCP broker, packed
fused-program scoring on the device pool, GBDT retraining — are
deterministic by seeding, so the whole timeline replays bit-identically:
the drill runs it twice and compares digests.

Convention matches the five sibling drills: full summary JSON, then a
compact (<2 KB) verdict as the FINAL stdout line.
"""

from __future__ import annotations

import dataclasses
import hashlib
import heapq
import json
import math
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["ChaosDrillConfig", "apply_chaos_settings", "run_chaos_drill",
           "compact_chaos_summary"]

_SPEEDUP = (1.0, 2.0, 4.0, 8.0)     # virtual capacity per ladder rung


@dataclasses.dataclass
class ChaosDrillConfig:
    """Drill sizes. Defaults = the full drill; ``fast()`` = tier-1."""

    seed: int = 11
    n_devices: int = 4
    inflight_depth: int = 2
    num_users: int = 600
    num_merchants: int = 200
    batch: int = 64
    max_delay_ms: float = 120.0       # virtual assembler deadline
    # deterministic service-cost model (virtual ms per dispatched batch)
    base_ms: float = 10.0
    per_txn_ms: float = 1.25
    # offered load: baseline rate + the flash-crowd envelope (multiples of
    # the level-0 virtual capacity at `batch`)
    tps: float = 280.0
    flash_s: float = 2.4
    flash_mult: float = 2.6
    flash_burst_mult: float = 1.6
    # phase sizes (transactions)
    n_train: int = 1536
    n_healthy: int = 1152
    n_outage: int = 512
    n_pool: int = 384
    n_ring: int = 1664
    n_recovery: int = 2560
    # fault windows (virtual seconds, relative to their phase starts)
    outage_lead_s: float = 0.2
    outage_s: float = 1.0
    label_stall_s: float = 2.0
    replica_faults: int = 1
    slow_device_ms: float = 30.0
    # fraud ring
    ring_rate: float = 0.10
    ring_members: int = 24
    ring_merchants: int = 6
    ring_devices: int = 4
    ring_ips: int = 3
    # incumbent + retrain
    n_trees: int = 32
    tree_depth: int = 4
    # feedback plane
    sliding_window: int = 512
    fading_gamma: float = 0.998
    auc_drop: float = 0.10
    # the floor sits just under THIS config's settled sliding AUC (the
    # fast incumbent settles lower — fewer trees, smaller window): a
    # HALF-learned ring (first candidate promoted before most ring labels
    # landed) leaves the window visibly depressed, so the policy keeps
    # re-triggering — and the gate keeps judging — until a candidate that
    # actually ranks the ring serves. Early noisy windows also trip it;
    # those candidates are honestly REFUSED by the non-regression gate.
    auc_floor: float = 0.92
    min_labels: int = 256
    # short virtual cooldown: the gate may honestly REFUSE the first
    # candidate (too few ring labels in its training segment yet) and
    # pass a later, better-informed one while the stream still flows
    cooldown_s: float = 3.0
    label_delay_scale: float = 2e-6
    # second, fresh run compared digest-for-digest against the first
    replay_check: bool = True

    @classmethod
    def fast(cls) -> "ChaosDrillConfig":
        """Tier-1 smoke sizes: every phase and every fault still runs."""
        return cls(n_devices=2, n_train=1024, n_healthy=896, flash_s=1.6,
                   n_outage=384, n_pool=256, n_ring=1280, n_recovery=1536,
                   n_trees=24, sliding_window=448, min_labels=224,
                   auc_floor=0.82)

    # ------------------------------------------------------------- derived
    def cost_s(self, n: int, level: int) -> float:
        """Virtual service cost of one dispatched batch at a ladder rung."""
        return ((self.base_ms + n * self.per_txn_ms) / 1e3) \
            / _SPEEDUP[min(level, len(_SPEEDUP) - 1)]

    def capacity_tps(self) -> float:
        """Level-0 sustainable rate at the configured batch size."""
        return self.batch / self.cost_s(self.batch, 0)


def apply_chaos_settings(cfg: ChaosDrillConfig, s) -> ChaosDrillConfig:
    """Overlay ``utils/config.ChaosSettings`` (the ``chaos.*`` block of a
    JSON config file, reached via ``rtfd chaos-drill --config``) onto a
    drill config. All of the settings are virtual-clock quantities, so
    they reshape the replayed fault timeline deterministically."""
    return dataclasses.replace(
        cfg, seed=s.seed, outage_s=s.broker_outage_s,
        label_stall_s=s.label_stall_s, flash_mult=s.flash_crowd_mult,
        flash_burst_mult=s.flash_burst_mult, ring_rate=s.ring_rate,
        ring_members=s.ring_members, ring_merchants=s.ring_merchants,
        ring_devices=s.ring_devices, ring_ips=s.ring_ips,
        replica_faults=s.replica_faults, slow_device_ms=s.slow_device_ms)


def _rank_auc(scores: List[float], labels: List[bool]) -> float:
    """Tie-averaged Mann-Whitney AUC (host arithmetic, deterministic)."""
    y = np.asarray(labels, bool)
    s = np.asarray(scores, float)
    n_pos = int(y.sum())
    n_neg = int(len(y) - n_pos)
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    _, inv, counts = np.unique(s, return_inverse=True, return_counts=True)
    avg_rank = np.cumsum(counts) - (counts - 1) / 2.0
    r = avg_rank[inv]
    return float((r[y].sum() - n_pos * (n_pos + 1) / 2.0)
                 / (n_pos * n_neg))


def _train_incumbent(cfg: ChaosDrillConfig, gen, scorer) -> Dict[str, Any]:
    """Historical labeled segment through the production assemble path →
    deployed trees + iforest (the feedback-drill recipe, chaos-sized)."""
    import jax

    from realtime_fraud_detection_tpu.models.isolation_forest import (
        IsolationForestTrainer,
    )
    from realtime_fraud_detection_tpu.training import GBDTTrainer

    xs, ys = [], []
    done, ts = 0, 0.0
    while done < cfg.n_train:
        n = min(cfg.batch, cfg.n_train - done)
        recs = gen.generate_batch(n)
        batch = scorer.assemble(recs, now=ts)
        xs.append(np.asarray(batch.features))
        ys.append(np.asarray([bool(r.get("is_fraud")) for r in recs],
                             np.float32))
        for r in recs:
            scorer.velocity.update(str(r.get("user_id", "")),
                                   float(r.get("amount", 0.0)), ts)
        done += n
        ts += n / cfg.tps
    x = np.concatenate(xs)
    y = np.concatenate(ys)
    trees = GBDTTrainer(n_estimators=cfg.n_trees, max_depth=cfg.tree_depth,
                        seed=cfg.seed).fit(x, y)
    iforest = IsolationForestTrainer(n_estimators=48,
                                     seed=cfg.seed + 1).fit(
        x[y < 0.5][:4000])
    # rtfd-lint: allow[lock-order] drill is single-threaded here (no batch in flight during the swap)
    scorer.set_models(scorer.models.replace(trees=trees, iforest=iforest))
    jax.block_until_ready(scorer.models.trees)
    return {"rows": int(len(y)), "fraud_rate": round(float(y.mean()), 4),
            "virtual_end_s": ts}


def _build_schedule(cfg: ChaosDrillConfig, gen, t0: float,
                    ) -> Tuple[List[Tuple[float, Dict[str, Any]]],
                               Dict[str, float], Any,
                               Dict[str, Tuple[str, bool]]]:
    """The full arrival timeline, phase by phase (generation order is part
    of the seeded state, so the ring activates mid-sequence exactly as it
    would mid-stream). Returns (schedule, phase marks, live ring, truth) —
    ``truth`` maps txn_id -> (phase, is_fraud): the drill's own labeled
    ledger for the phase-scoped quality measurement."""
    from realtime_fraud_detection_tpu.sim.arrivals import (
        DiurnalBurstConfig,
        DiurnalBurstProcess,
    )
    from realtime_fraud_detection_tpu.sim.fraud_patterns import (
        FraudRingConfig,
    )

    sched: List[Tuple[float, Dict[str, Any]]] = []
    marks: Dict[str, float] = {}
    truth: Dict[str, Tuple[str, bool]] = {}
    phase = ["healthy"]
    t = t0

    def note(txns) -> None:
        for txn in txns:
            truth[str(txn["transaction_id"])] = (
                phase[0], bool(txn.get("is_fraud")))

    def uniform(n: int, start: float) -> float:
        txns = gen.generate_batch(n)
        note(txns)
        for i, txn in enumerate(txns):
            sched.append((start + i / cfg.tps, txn))
        return start + n / cfg.tps

    marks["healthy"] = t
    t = uniform(cfg.n_healthy, t)

    marks["flash"] = t
    phase[0] = "flash"
    proc = DiurnalBurstProcess(DiurnalBurstConfig(
        trough_tps=cfg.tps,
        peak_tps=cfg.flash_mult * cfg.capacity_tps(),
        period_s=cfg.flash_s,
        burst_every_s=cfg.flash_s / 2.0,
        burst_offset_s=cfg.flash_s / 3.0,
        burst_duration_s=cfg.flash_s / 8.0,
        burst_mult=cfg.flash_burst_mult,
        t0=t,
    ), seed=cfg.seed + 2)
    times = proc.generate(cfg.flash_s)
    flash_txns = gen.generate_batch(len(times))
    note(flash_txns)
    sched.extend(zip(times.tolist(), flash_txns))
    t += cfg.flash_s

    marks["outage"] = t
    phase[0] = "outage"
    t = uniform(cfg.n_outage, t)
    # margin so the heal lands while arrivals still flow
    t = max(t, marks["outage"] + cfg.outage_lead_s + cfg.outage_s + 0.3)

    marks["pool"] = t
    phase[0] = "pool"
    t = uniform(cfg.n_pool, t)

    marks["ring"] = t
    phase[0] = "ring"
    ring = gen.inject_fraud_ring(FraudRingConfig(
        n_members=cfg.ring_members, n_merchants=cfg.ring_merchants,
        n_devices=cfg.ring_devices, n_ips=cfg.ring_ips,
        rate=cfg.ring_rate))
    t = uniform(cfg.n_ring, t)

    marks["recovery"] = t
    phase[0] = "recovery"
    t = uniform(cfg.n_recovery, t)
    marks["end"] = t
    return sched, marks, ring, truth


def _run_once(cfg: ChaosDrillConfig, devices) -> Dict[str, Any]:
    """One full pass of the fault timeline; returns the raw outcome
    (summary fields + the replay digest)."""
    from realtime_fraud_detection_tpu.chaos.faults import (
        BrokerReplicaOutage,
        ChaosPlan,
        DeviceReplicaDeath,
        FaultWindow,
        LabelStall,
        SlowDevice,
    )
    from realtime_fraud_detection_tpu.feedback.plane import FeedbackPlane
    from realtime_fraud_detection_tpu.obs.tracing import Tracer
    from realtime_fraud_detection_tpu.qos import QosPlane
    from realtime_fraud_detection_tpu.scoring import (
        DevicePool,
        FraudScorer,
        ScorerConfig,
    )
    from realtime_fraud_detection_tpu.sim.simulator import (
        TransactionGenerator,
    )
    from realtime_fraud_detection_tpu.stream import topics as T
    from realtime_fraud_detection_tpu.stream.job import JobConfig, StreamJob
    from realtime_fraud_detection_tpu.stream.microbatch import (
        MicrobatchAssembler,
    )
    from realtime_fraud_detection_tpu.stream.netbroker import (
        BrokerServer,
        NetBrokerClient,
    )
    from realtime_fraud_detection_tpu.utils.config import (
        Config,
        FeedbackSettings,
        QosSettings,
        TracingSettings,
    )

    capacity = cfg.capacity_tps()

    # ---- serving pair + incumbent (the feedback-drill production baseline)
    app_config = Config()
    for name, mc in app_config.models.items():
        mc.enabled = name in ("xgboost_primary", "isolation_forest")
    app_config.models["xgboost_primary"].weight = 0.8
    app_config.models["isolation_forest"].weight = 0.2

    gen = TransactionGenerator(num_users=cfg.num_users,
                               num_merchants=cfg.num_merchants,
                               seed=cfg.seed, tps=cfg.tps)
    scorer = FraudScorer(app_config,
                         scorer_config=ScorerConfig(text_len=16,
                                                    tokenizer="word"))
    scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    incumbent = _train_incumbent(cfg, gen, scorer)

    # pool AFTER the incumbent deploys (replicas copy the live params)
    pool = DevicePool(scorer, devices=devices,
                      inflight_depth=cfg.inflight_depth)

    # ---- real networked broker: primary + synchronous replica, min_isr=2
    replica = BrokerServer(port=0, role="replica").start()
    primary = BrokerServer(port=0, min_isr=2).start()
    primary.add_replica("127.0.0.1", replica.port)
    producer = NetBrokerClient(port=primary.port, reconnect_attempts=2)
    job_client = NetBrokerClient(port=primary.port, reconnect_attempts=2)
    outage = None     # bound inside the try; the finally guards on None
    try:
        # ---- planes on one virtual clock
        clock = [incumbent["virtual_end_s"]]
        vclock = lambda: clock[0]                                  # noqa: E731

        w = max(1, len(devices) * cfg.inflight_depth)   # in-flight window
        steady_e2e_ms = (cfg.max_delay_ms
                         + (w + 1) * cfg.cost_s(cfg.batch, 0) * 1e3)
        qos_settings = QosSettings(
            enabled=True,
            budget_ms=4.0 * steady_e2e_ms,
            assemble_margin_ms=0.5 * steady_e2e_ms,
            admission_rate=capacity,
            admission_burst=capacity * 0.20,
            high_value_amount=500.0,
            low_value_amount=25.0,
            ladder_high_backlog=(w + 3) * cfg.batch,
            ladder_low_backlog=(w + 1) * cfg.batch,
            ladder_patience=3,
            ladder_up_patience=10,
        )
        plane = QosPlane(qos_settings)
        # rungs 1-2 are the capacity levers for this serving pair (the heavy
        # branches are already disabled); rules_only would change the scored
        # DISTRIBUTION mid-timeline and conflate the flash window with the
        # ring-quality measurement, so the drill caps the ladder below it
        plane.ladder.config.max_level = 2

        tracer = Tracer(TracingSettings(
            enabled=True, ring_size=16384, slowest_n=16,
            slo_objective_ms=1.25 * steady_e2e_ms, slo_objective_frac=0.95,
            slo_fast_window_s=3.0, slo_slow_window_s=12.0, slo_bucket_s=0.25,
            slo_burn_threshold=2.0, slo_gate_patience=3,
            slo_gate_up_patience=10), clock=vclock)

        fb = FeedbackPlane(FeedbackSettings(
            enabled=True,
            label_horizon_s=120.0, label_ooo_s=0.5, pred_ooo_s=0.5,
            label_delay_scale=cfg.label_delay_scale,
            buffer_size=max(cfg.n_healthy + cfg.n_ring + cfg.n_recovery, 4096),
            sliding_window=cfg.sliding_window, fading_gamma=cfg.fading_gamma,
            operating_threshold=0.5,
            auc_drop=cfg.auc_drop, auc_floor=cfg.auc_floor,
            min_labels=cfg.min_labels, cooldown_s=cfg.cooldown_s,
            retrain_trees=cfg.n_trees, retrain_depth=cfg.tree_depth + 1,
            gate_min_positives=12,
            gate_select_frac=0.1, gate_holdout_frac=0.15,
        ), scorer=scorer, config=app_config, clock=vclock)

        job = StreamJob(job_client, scorer, JobConfig(
            max_batch=cfg.batch, emit_features=False, emit_enriched=False,
            qos=plane, feedback=fb, tracing=tracer))
        job.assembler = MicrobatchAssembler(
            job.consumer, max_batch=cfg.batch, max_delay_ms=cfg.max_delay_ms,
            clock=vclock, budget=plane.budget, budget_clock=vclock)

        # ---- the seeded timeline + fault plan
        sched, marks, ring, truth = _build_schedule(cfg, gen, clock[0])
        t_outage = marks["outage"] + cfg.outage_lead_s
        t_pool = marks["pool"]
        # device-fault windows scale with the pool phase so the round-robin
        # rotation is guaranteed to land batches on the victim inside them
        pool_phase_s = cfg.n_pool / cfg.tps
        plan = ChaosPlan([
            FaultWindow("flash_crowd", "arrival_spike",
                        marks["flash"], marks["outage"]),
            FaultWindow("broker_outage", "broker",
                        t_outage, t_outage + cfg.outage_s),
            FaultWindow("replica_death", "device_pool",
                        t_pool + 0.05, t_pool + 0.05 + 0.55 * pool_phase_s),
            FaultWindow("slow_device", "device_pool",
                        t_pool + 0.7 * pool_phase_s,
                        t_pool + 0.9 * pool_phase_s),
            FaultWindow("label_stall", "labels",
                        marks["ring"], marks["ring"] + cfg.label_stall_s),
        ])
        outage = BrokerReplicaOutage(
            primary, replica,
            lambda: BrokerServer(port=0, role="replica").start())
        stall = LabelStall()
        victim = 1 % len(devices)
        plan.bind("broker_outage", outage)
        plan.bind("replica_death",
                  DeviceReplicaDeath(pool, victim, cfg.replica_faults))
        plan.bind("slow_device",
                  SlowDevice(pool, victim, cfg.slow_device_ms / 1e3, n=2))
        plan.bind("label_stall", stall)

        # ---- drive state
        label_heap: List = []
        lseq = [0]
        label_retry: deque = deque()
        txn_retry: deque = deque()
        produced_ids: List[str] = []
        produce_failures = [0]
        fanout_failures = 0
        batch_integrity_ok = True
        ladder_trace: List[int] = []
        burn_trace: List[float] = []
        auc_trace: List[Tuple[float, float]] = []
        verdicts: List[Dict[str, Any]] = []
        in_flight: deque = deque()
        next_i = 0
        idle = 0.01
        max_burn = [0.0]

        def push_labels(due: List[Tuple[float, Dict[str, Any]]]) -> None:
            txns = [t for _, t in due]
            ts_list = [ts for ts, _ in due]
            for ev in gen.label_events(txns, event_ts=ts_list,
                                       delay_scale=cfg.label_delay_scale):
                heapq.heappush(label_heap, (ev["label_ts"], lseq[0], ev))
                lseq[0] += 1

        # Producer outage mode: a produce that fails NotEnoughReplicas has
        # still APPENDED its records above the high watermark — re-attempting
        # every tick would stack one invisible copy per attempt. After the
        # first failure the producer buffers and probes broker health (ISR >=
        # min_isr via the status op) before retrying — the client-side analog
        # of a real producer's bounded retry-with-backoff.
        outage_mode = [False]

        def broker_healthy() -> bool:
            try:
                st = producer.status()
                return int(st.get("isr", 1)) >= int(st.get("min_isr", 1))
            except (RuntimeError, ConnectionError, OSError):
                return False

        def produce_txns(items: List[Tuple[str, Dict[str, Any], float]]) -> bool:
            try:
                producer.produce_batch_stamped(T.TRANSACTIONS, items)
                return True
            except (RuntimeError, ConnectionError, OSError):
                produce_failures[0] += 1
                outage_mode[0] = True
                return False

        def release_labels(now: float) -> int:
            if stall.active:
                return 0
            released = 0
            due = []
            while label_heap and label_heap[0][0] <= now:
                due.append(heapq.heappop(label_heap)[2])
            if outage_mode[0]:
                label_retry.extend(due)
                return 0
            due.extend(label_retry)
            label_retry.clear()
            if not due:
                return 0
            items = [(ev["transaction_id"], ev, ev["label_ts"]) for ev in due]
            try:
                producer.produce_batch_stamped(T.LABELS, items)
                released = len(items)
            except (RuntimeError, ConnectionError, OSError):
                produce_failures[0] += 1
                outage_mode[0] = True
                label_retry.extend(due)
            return released

        def observe_auc(now: float) -> None:
            a = fb.evaluator.auc()
            if not math.isnan(a) and len(fb.evaluator) >= cfg.min_labels:
                auc_trace.append((now, round(float(a), 4)))

        def complete_one() -> None:
            nonlocal fanout_failures, batch_integrity_ok
            ctx = in_flight.popleft()
            if ctx is None:
                return
            want = [str(r.value.get("transaction_id", "")) for r in ctx.fresh]
            try:
                results = job.complete_batch(ctx, now=clock[0])
                got = [str(r.get("transaction_id", "")) for r in results
                       if not (r.get("explanation") or {}).get(
                           "validation_errors")]
                if want and got[-len(want):] != want:
                    batch_integrity_ok = False
            except Exception:  # noqa: BLE001 — the broker is DOWN by design
                # crash-recovery semantics: fan-out failed mid-batch, offsets
                # were not committed — rewind to committed; the scored records
                # replay through the txn-cache dedupe (re-emitted from cache)
                fanout_failures += 1
                job.consumer.seek_to_committed()
            burn = tracer.slo.burn_rate(tracer.settings.slo_fast_window_s)
            burn_trace.append(round(burn, 3))
            max_burn[0] = max(max_burn[0], burn)
            observe_auc(clock[0])
            if fb.pending_trigger is not None:
                v = fb.react(now=clock[0])
                if v is not None:
                    verdicts.append(v)

        # recovery bookkeeping (virtual instants, None until observed)
        recovered_at: Dict[str, Optional[float]] = {
            "flash_crowd": None, "broker_outage": None, "replica_death": None}

        # ---- the drive loop --------------------------------------------------
        while True:
            now = clock[0]
            plan.poll(now)
            tracer.set_fault_context(",".join(plan.active(now)))

            due: List[Tuple[float, Dict[str, Any]]] = []
            while next_i < len(sched) and sched[next_i][0] <= now:
                due.append(sched[next_i])
                next_i += 1
            if due:
                push_labels(due)
                items = [(str(t["user_id"]), t, ts) for ts, t in due]
                produced_ids.extend(str(t["transaction_id"]) for _, t in due)
                if outage_mode[0]:
                    txn_retry.extend(items)
                elif not produce_txns(items):
                    txn_retry.extend(items)
            if outage_mode[0] and broker_healthy():
                outage_mode[0] = False
            if txn_retry and not outage_mode[0]:
                retry = list(txn_retry)
                txn_retry.clear()
                if not produce_txns(retry):
                    txn_retry.extend(retry)
                elif recovered_at["broker_outage"] is None:
                    recovered_at["broker_outage"] = now
                    plan.note_recovered("broker_outage", now)
            if release_labels(now):
                job.drain_labels()
                fb.check_trigger(now=now)
                if fb.pending_trigger is not None:
                    v = fb.react(now=now)
                    if v is not None:
                        verdicts.append(v)
                observe_auc(now)

            batch = job.assembler.next_batch(block=False)
            if not batch and next_i >= len(sched) and not txn_retry:
                batch = job.assembler.flush()
            if batch:
                ctx = job.dispatch_batch(batch, now=now)
                level = plane.effective_level()
                ladder_trace.append(level)
                clock[0] += cfg.cost_s(len(batch), level)
                if recovered_at["flash_crowd"] is None and level == 0 \
                        and now > marks["outage"]:
                    recovered_at["flash_crowd"] = now
                    plan.note_recovered("flash_crowd", now)
                in_flight.append(ctx)
                while len(in_flight) >= w:
                    complete_one()
                continue
            if in_flight:
                complete_one()
                continue
            if next_i >= len(sched) and not txn_retry and not label_heap \
                    and not label_retry and job.consumer.lag() == 0:
                break
            # idle: jump to the next scheduled event (arrival, label release,
            # fault transition), never backwards
            targets = [now + 0.25]
            if next_i < len(sched):
                targets.append(sched[next_i][0])
            if label_heap and not stall.active:
                targets.append(label_heap[0][0])
            for fw in plan.windows:
                for edge in (fw.t_start, fw.t_end):
                    if edge > now:
                        targets.append(edge)
            clock[0] = max(now + idle, min(targets))

        # pool recovery: the dead replica was revived by the plan; retries
        # were absorbed mid-flight
        pool_stats = pool.stats()
        if pool_stats["healthy"] == len(devices) and pool_stats["retries"] > 0:
            recovered_at["replica_death"] = clock[0]
            plan.note_recovered("replica_death", clock[0])

        # ---- settle the delayed-label tail, then quiet-period recovery -------
        def settle_labels(horizon_s: float = 30.0) -> None:
            t_end = clock[0] + horizon_s
            while (label_heap or label_retry) and clock[0] < t_end:
                nxt = label_heap[0][0] if label_heap else clock[0] + 0.25
                clock[0] = min(max(nxt, clock[0] + 0.25), t_end)
                if release_labels(clock[0]):
                    job.drain_labels()
                    fb.check_trigger(now=clock[0])
                if fb.pending_trigger is not None:
                    v = fb.react(now=clock[0])
                    if v is not None:
                        verdicts.append(v)
                observe_auc(clock[0])

        settle_labels()
        # a drained system: backlog reads zero and the SLO window ages out its
        # violations — both hysteresis gates must walk back to rung 0 / off
        for _ in range(48):
            if plane.ladder.level == 0 and not plane.slo_engaged:
                break
            clock[0] += tracer.settings.slo_bucket_s
            plane.observe_backlog(0)
            ts = tracer.settings
            plane.observe_slo_burn(
                tracer.slo.burn_rate(ts.slo_fast_window_s),
                threshold=ts.slo_burn_threshold,
                patience=ts.slo_gate_patience,
                up_patience=ts.slo_gate_up_patience)
            # rtfd-lint: allow[lock-order] drill drives the plane from one thread on the virtual clock
            plane.apply_degradation(scorer)
        final_burn = tracer.slo.burn_rate(tracer.settings.slo_fast_window_s)

        # ---- ledger: read the predictions + transactions topics back ---------
        preds: List[Tuple[str, float, str, str]] = []   # (id, score, dec, kind)
        n_parts = job_client.partitions(T.PREDICTIONS)
        for p in range(n_parts):
            off = 0
            while True:
                recs = job_client.read(T.PREDICTIONS, p, off, 2048)
                if not recs:
                    break
                off = recs[-1].offset + 1
                for r in recs:
                    v = r.value if isinstance(r.value, dict) else {}
                    ex = v.get("explanation") or {}
                    kind = ("shed" if ex.get("shed")
                            else "replayed" if ex.get("replayed_from_cache")
                            else "error" if ex.get("error")
                            else "scored")
                    preds.append((str(v.get("transaction_id", "")),
                                  round(float(v.get("fraud_score", 0.0)), 6),
                                  str(v.get("decision", "")), kind))

        by_id: Dict[str, Dict[str, int]] = {}
        for tid, _, _, kind in preds:
            by_id.setdefault(tid, {})[kind] = by_id.get(tid, {}).get(kind, 0) + 1
        produced_unique = set(produced_ids)
        covered = set(by_id)
        # "effectively once": every delivered transaction is accounted for on
        # the predictions topic, and no transaction was device-scored twice —
        # at most ONE non-replayed scored/error record per id (replayed-from-
        # cache re-emissions and shed decisions are the documented
        # at-least-once surplus, never double scoring)
        fresh_counts = [kinds.get("scored", 0) + kinds.get("error", 0)
                        for kinds in by_id.values()]
        shed_only = sum(1 for kinds in by_id.values()
                        if set(kinds) == {"shed"})
        effectively_once = (
            covered == produced_unique
            and all(c <= 1 for c in fresh_counts))
        # offset accounting: every transaction offset acked, visible, committed
        tx_ends = job_client.end_offsets(T.TRANSACTIONS)
        committed = [job_client.committed(job.config.group_id,
                                          T.TRANSACTIONS, p)
                     for p in range(len(tx_ends))]
        offsets_gap_free = committed == tx_ends

        # high-value sheds: the admission contract, checked from the metrics
        shed_by: Dict[str, int] = {}
        for labels, count in plane.metrics.qos_shed.by_label():
            shed_by[f"{labels.get('priority')}:{labels.get('reason')}"] = \
                int(count)
        high_sheds = sum(n for k, n in shed_by.items()
                         if k.startswith("high:"))

        # ring quality story, two measurements with different jobs:
        #  - LIVE signal (prequential sliding window): baseline = the last
        #    observation before the ring activates, dip = the worst after it —
        #    this is the monitoring signal that fires the retrain trigger;
        #  - RECOVERY (the drill's own labeled ledger): per-phase rank AUC of
        #    generator truth x served scores. The prequential window at drain
        #    time fills with long-delay labels from PRE-promotion ring traffic,
        #    so it lags the deployed blend by a label horizon; phase-scoping on
        #    `truth` measures what the retrained blend actually served during
        #    the recovery phase.
        baseline_auc = float("nan")
        for t, a in auc_trace:
            if t <= marks["ring"]:
                baseline_auc = a
        ring_dip = min((a for t, a in auc_trace if t > marks["ring"]),
                       default=float("nan"))
        final_auc = auc_trace[-1][1] if auc_trace else float("nan")
        score_by_id: Dict[str, float] = {}
        for tid, score, _, kind in preds:
            if kind in ("scored", "replayed") and tid not in score_by_id:
                score_by_id[tid] = score
        phase_samples: Dict[str, Tuple[List[float], List[bool]]] = {}
        for tid, (ph, y) in truth.items():
            s = score_by_id.get(tid)
            if s is not None:
                ss, yy = phase_samples.setdefault(ph, ([], []))
                ss.append(s)
                yy.append(y)
        phase_auc = {ph: round(_rank_auc(ss, yy), 4)
                     for ph, (ss, yy) in sorted(phase_samples.items())
                     if not math.isnan(_rank_auc(ss, yy))}
        promotions = [v for v in verdicts
                      if v.get("passed") and "promoted" in v
                      and v.get("ts", 0.0) >= marks["ring"]]

        # fault-window trace attribution (flight recorder)
        fault_traces: Dict[str, int] = {}
        for ct in tracer.traces():
            f = (ct.meta or {}).get("fault")
            if f:
                for name in str(f).split(","):
                    fault_traces[name] = fault_traces.get(name, 0) + 1

        # degraded-mode service quality (the bench `chaos` stage's numbers):
        # e2e p99 + virtual throughput of SCORED traffic inside any fault
        # window vs in the post-fault recovery phase, straight off the
        # fault-attributed flight recorder
        def _p99_ms(vals: List[float]) -> Optional[float]:
            return (round(float(np.percentile(np.asarray(vals), 99.0)), 3)
                    if vals else None)

        scored_traces = tracer.traces(terminal="scored")
        in_fault = [ct.e2e_ms for ct in scored_traces
                    if (ct.meta or {}).get("fault")]
        post_fault = [ct.e2e_ms for ct in scored_traces
                      if not (ct.meta or {}).get("fault")
                      and ct.t_start >= marks["recovery"]]
        fault_span_s = sum(w.t_end - w.t_start for w in plan.windows)
        recovery_span_s = marks["end"] - marks["recovery"]
        degraded = {
            "in_fault": {"n": len(in_fault), "p99_ms": _p99_ms(in_fault),
                         "tps": round(len(in_fault) / max(fault_span_s, 1e-9),
                                      1)},
            "post_fault": {"n": len(post_fault),
                           "p99_ms": _p99_ms(post_fault),
                           "tps": round(len(post_fault)
                                        / max(recovery_span_s, 1e-9), 1)},
        }

        # chaos_* Prometheus mirror (the series the obs plane exposes)
        plane.metrics.sync_chaos(plan.snapshot(clock[0]))

        digest = hashlib.sha256(json.dumps({
            "preds": preds,
            "ladder": ladder_trace,
            "sheds": sorted(shed_by.items()),
            "committed": committed,
            "auc": auc_trace,
            "promoted": [v.get("promoted") for v in promotions],
        }, sort_keys=True).encode()).hexdigest()

        outcome = {
            "incumbent": incumbent,
            "capacity_tps": round(capacity, 1),
            "marks": {k: round(v, 3) for k, v in marks.items()},
            "plan": plan.snapshot(clock[0]),
            "produced": len(produced_ids),
            "produced_unique": len(produced_unique),
            "scored": job.counters["scored"],
            "shed": job.counters["shed"],
            "duplicates_skipped": job.counters["duplicates_skipped"],
            "shed_by_priority_reason": shed_by,
            "high_value_sheds": int(high_sheds),
            "shed_only_ids": int(shed_only),
            "produce_failures": int(produce_failures[0]),
            "fanout_failures": int(fanout_failures),
            "effectively_once": bool(effectively_once),
            "offsets_gap_free": bool(offsets_gap_free),
            "tx_end_offsets": tx_ends,
            "tx_committed": committed,
            "max_ladder_level": max(ladder_trace, default=0),
            "final_ladder_level": plane.effective_level(),
            "max_burn": round(max_burn[0], 3),
            "final_burn": round(final_burn, 3),
            "pool": pool_stats,
            "batch_integrity_ok": bool(batch_integrity_ok),
            "ring": ring.stats(),
            "label_join": fb.join.stats(),
            "label_stalls": stall.stalls,
            "baseline_auc": (None if math.isnan(baseline_auc)
                             else round(baseline_auc, 4)),
            "ring_dip_auc": (None if math.isnan(ring_dip)
                             else round(ring_dip, 4)),
            "final_auc": (None if math.isnan(final_auc)
                          else round(final_auc, 4)),
            "phase_auc": phase_auc,
            "ring_promotions": len(promotions),
            "gate_verdicts": len(verdicts),
            "policy": dict(fb.counters),
            "verdict_tail": [
                {"ts": round(float(v.get("ts", 0.0)), 2),
                 "type": v.get("type"),
                 "passed": v.get("passed"),
                 "reason": v.get("reason"),
                 "trigger_reason": v.get("trigger_reason")}
                for v in verdicts[-4:]],
            "fault_window_traces": fault_traces,
            "degraded": degraded,
            "recovered_at": {k: (None if v is None else round(v, 3))
                             for k, v in recovered_at.items()},
            "broker_outages": outage.outages,
            "virtual_duration_s": round(clock[0], 2),
            "digest": digest,
        }
        return outcome
    finally:
        # teardown (fresh servers per run keep the replay hermetic) runs
        # even when the drive section raises: the in-process tier-1 smoke
        # and the replay's second run must never inherit live listener
        # threads or sockets from a failed first run
        producer.close()
        job_client.close()
        primary.stop()
        replica.stop()          # already-stopped servers tolerate stop()
        if outage is not None and outage.restored_replica is not None:
            outage.restored_replica.stop()


def run_chaos_drill(config: Optional[ChaosDrillConfig] = None,
                    fast: bool = False) -> Dict[str, Any]:
    """Run the combined recovery drill (twice, when ``replay_check``) and
    assemble the verdict."""
    import jax

    cfg = config or (ChaosDrillConfig.fast() if fast else ChaosDrillConfig())
    devices = jax.devices()
    if len(devices) < cfg.n_devices:
        raise RuntimeError(
            f"chaos drill needs {cfg.n_devices} devices, found "
            f"{len(devices)} — run via `rtfd chaos-drill` (it re-execs on "
            f"a virtual host platform) or set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count="
            f"{cfg.n_devices}")
    devices = devices[:cfg.n_devices]

    first = _run_once(cfg, devices)
    replay_identical = None
    if cfg.replay_check:
        second = _run_once(cfg, devices)
        replay_identical = second["digest"] == first["digest"]

    checks = {
        "zero_high_value_sheds": first["high_value_sheds"] == 0,
        "low_priority_sheds_occurred": first["shed"] > 0,
        "ladder_engaged": first["max_ladder_level"] >= 1,
        "ladder_recovered": first["final_ladder_level"] == 0,
        "burn_spiked": first["max_burn"] > 2.0,
        "burn_recovered": first["final_burn"] < 1.0,
        "broker_outage_hit": first["produce_failures"] > 0
        and first["broker_outages"] >= 1,
        "effectively_once": first["effectively_once"],
        "offsets_gap_free": first["offsets_gap_free"],
        "pool_retry_absorbed": first["pool"]["retries"] >= 1,
        "pool_healthy_again": (first["pool"]["healthy"]
                               == first["pool"]["n_devices"]),
        "fifo_batch_integrity": first["batch_integrity_ok"],
        "ring_auc_dipped": (first["baseline_auc"] is not None
                            and first["ring_dip_auc"] is not None
                            and first["baseline_auc"] - first["ring_dip_auc"]
                            >= cfg.auc_drop / 2),
        "ring_promoted_via_gate": first["ring_promotions"] >= 1,
        # recovery is judged on what the retrained blend SERVED during the
        # recovery phase (the drill's own truth ledger), against the same
        # ledger's healthy-phase baseline — the prequential window at drain
        # time still trails pre-promotion ring labels by a label horizon
        "ring_auc_recovered": (
            first["phase_auc"].get("recovery") is not None
            and first["phase_auc"].get("healthy") is not None
            and first["phase_auc"]["recovery"]
            >= first["phase_auc"]["healthy"] - 0.01),
        "fault_windows_traced": len(first["fault_window_traces"]) >= 3,
    }
    if replay_identical is not None:
        checks["replay_bit_identical"] = bool(replay_identical)

    summary: Dict[str, Any] = {
        "metric": "chaos_drill",
        "passed": all(bool(v) for v in checks.values()),
        "checks": checks,
        "n_devices": cfg.n_devices,
        "replay_identical": replay_identical,
        **first,
    }
    return summary


def compact_chaos_summary(summary: Dict[str, Any]) -> Dict[str, Any]:
    """The <2 KB final-stdout-line digest (bench.py convention: full
    result on the preceding line, compact parseable verdict last)."""
    compact = {
        "metric": "chaos_drill",
        "passed": summary.get("passed"),
        "checks": {k: bool(v)
                   for k, v in (summary.get("checks") or {}).items()},
        "produced": summary.get("produced"),
        "scored": summary.get("scored"),
        "shed": summary.get("shed"),
        "high_value_sheds": summary.get("high_value_sheds"),
        "produce_failures": summary.get("produce_failures"),
        "max_ladder_level": summary.get("max_ladder_level"),
        "max_burn": summary.get("max_burn"),
        "final_burn": summary.get("final_burn"),
        "pool_retries": (summary.get("pool") or {}).get("retries"),
        "baseline_auc": summary.get("baseline_auc"),
        "ring_dip_auc": summary.get("ring_dip_auc"),
        "final_auc": summary.get("final_auc"),
        "phase_auc": summary.get("phase_auc"),
        "degraded": summary.get("degraded"),
        "virtual_duration_s": summary.get("virtual_duration_s"),
        "digest": (summary.get("digest") or "")[:16],
        "summary_of": "full result JSON on the preceding stdout line",
    }
    line = json.dumps(compact, separators=(",", ":"))
    while len(line.encode()) >= 2048:     # hard contract: < 2 KB, one line
        for victim in ("degraded", "phase_auc", "checks", "digest",
                       "summary_of"):
            if compact.pop(victim, None) is not None:
                break
        else:
            compact = {"metric": "chaos_drill",
                       "passed": summary.get("passed")}
        line = json.dumps(compact, separators=(",", ":"))
    return compact

"""Deterministic in-path link faults for the framing transports.

The chaos plane (chaos/faults.py) kills *things* — replicas, members,
devices, processes. This module degrades the *network* the distributed
fleet (PR 12) actually lives on: named links keyed by ``(role, peer)``
sit in the request path of the netbroker framing clients
(``stream/netbroker.NetBrokerClient``, ``cluster/handoff.HandoffClient``)
and inject, per frame:

- **added latency** (fixed + seeded jitter) and **slow-link throttling**
  (bytes/s — the delay scales with the frame size);
- **bounded drop-then-reconnect** (the next N matched sends fail with a
  connection reset, exercising the client's REAL reconnect machinery —
  bounded, so the link heals by itself);
- **partitions** — ``full`` (requests never reach the peer: refused at
  send) and ``one_way`` (the request reaches the peer and is APPLIED, but
  the response is lost: the caller observes a connection error, retries,
  and may duplicate the op — exactly the at-least-once ack-loss window of
  a real asymmetric partition).

Faults can be scoped with a ``match`` spec (``{"ops": [...], "topics":
[...]}``): a partition matched to the cluster control/events topics is the
drill's **asymmetric partition** — the worker is deaf to the coordinator
while its data path still reaches the broker (the zombie-writer scenario
the broker's producer generation fencing exists for; see
``stream/netbroker.py`` and docs/chaos.md).

Everything is driven from :class:`~realtime_fraud_detection_tpu.chaos.
faults.ChaosPlan` windows on the caller's clock — the link layer never
reads time itself (the poll clock and the sleep seam are injected), so a
seeded drill replays the identical fault timeline. The injectors
:class:`NetworkPartition` and :class:`LinkDegrade` register beside the
PR 8 set in ``chaos.__init__``.
"""

from __future__ import annotations

import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence

import numpy as np

from realtime_fraud_detection_tpu.chaos.faults import ChaosPlan, FaultWindow

__all__ = [
    "LinkState",
    "LinkFaultPlane",
    "NetworkPartition",
    "LinkDegrade",
    "ScheduledLink",
    "scheduled_link_from_spec",
]


def _match_frame(match: Optional[Mapping[str, Any]],
                 req: Mapping[str, Any]) -> bool:
    """Does a request frame fall under a fault's ``match`` spec?

    ``None`` matches everything. ``{"ops": [...]}`` restricts by wire op;
    ``{"topics": [...]}`` by the frame's topic (``topic`` or ``name``
    field — ``create_topic`` frames carry ``name``). Both given = AND."""
    if match is None:
        return True
    ops = match.get("ops") or ()
    if ops and req.get("op") not in ops:
        return False
    topics = match.get("topics") or ()
    if topics:
        topic = req.get("topic", req.get("name"))
        if topic not in topics:
            return False
    return True


class LinkState:
    """One named link's live fault state + counters.

    The framing clients call :meth:`before_send` under their connection
    lock and :meth:`after_recv` once a response frame arrived; both are
    cheap no-ops while no fault is armed. Thread-safe: a link is shared
    by every consumer of one client connection."""

    def __init__(self, role: str, peer: str,
                 sleep: Optional[Callable[[float], None]] = None,
                 seed: int = 0):
        self.role = str(role)
        self.peer = str(peer)
        self.name = f"{self.role}->{self.peer}"
        self._sleep = sleep if sleep is not None else time.sleep
        self._lock = threading.Lock()
        # jitter is a seeded per-link stream: replayable, de-correlated
        # across links by the (role, peer) identity mixed into the seed
        # (crc32, not hash() — str hashing is salted per process, and the
        # schedule must replay identically inside a fresh worker process)
        self._rng = np.random.default_rng(
            (int(seed) * 1_000_003
             + zlib.crc32(self.name.encode())) % (2**32))
        # live fault state
        self.partition_mode: Optional[str] = None    # "full" | "one_way"
        self._partition_match: Optional[Dict[str, Any]] = None
        self.latency_s = 0.0
        self.jitter_s = 0.0
        self.throttle_bytes_per_s = 0.0
        self.drop_remaining = 0
        self._degrade_match: Optional[Dict[str, Any]] = None
        # counters (cumulative — mirrored by sync_netfaults as deltas)
        self.windows_begun = 0
        self.delayed_sends = 0
        self.dropped_sends = 0
        self.partitioned_sends = 0
        self.lost_responses = 0
        self.throttled_bytes = 0

    # ------------------------------------------------------------- arming
    def set_partition(self, mode: str,
                      match: Optional[Mapping[str, Any]] = None) -> None:
        if mode not in ("full", "one_way"):
            raise ValueError(f"partition mode must be full|one_way, "
                             f"got {mode!r}")
        with self._lock:
            self.partition_mode = mode
            self._partition_match = dict(match) if match else None
            self.windows_begun += 1

    def clear_partition(self) -> None:
        with self._lock:
            self.partition_mode = None
            self._partition_match = None

    def set_degrade(self, latency_s: float = 0.0, jitter_s: float = 0.0,
                    throttle_bytes_per_s: float = 0.0, drop_next: int = 0,
                    match: Optional[Mapping[str, Any]] = None) -> None:
        if latency_s < 0 or jitter_s < 0 or throttle_bytes_per_s < 0 \
                or drop_next < 0:
            raise ValueError("degrade parameters must be >= 0")
        with self._lock:
            self.latency_s = float(latency_s)
            self.jitter_s = float(jitter_s)
            self.throttle_bytes_per_s = float(throttle_bytes_per_s)
            self.drop_remaining = int(drop_next)
            self._degrade_match = dict(match) if match else None
            self.windows_begun += 1

    def clear_degrade(self) -> None:
        with self._lock:
            self.latency_s = self.jitter_s = 0.0
            self.throttle_bytes_per_s = 0.0
            self.drop_remaining = 0
            self._degrade_match = None

    def active(self) -> bool:
        return (self.partition_mode is not None or self.latency_s > 0
                or self.throttle_bytes_per_s > 0 or self.drop_remaining > 0)

    # ----------------------------------------------------------- the path
    def before_send(self, req: Mapping[str, Any], nbytes: int = 0) -> None:
        """In-path hook BEFORE a frame is written. May sleep (latency /
        throttle) or raise ``ConnectionResetError`` (full partition /
        bounded drop) — the client's normal reconnect+retry machinery
        handles the error exactly as it would a real network fault."""
        delay = 0.0
        with self._lock:
            if self.partition_mode == "full" \
                    and _match_frame(self._partition_match, req):
                self.partitioned_sends += 1
                raise ConnectionResetError(
                    f"chaos: link {self.name} partitioned (full)")
            if _match_frame(self._degrade_match, req):
                if self.drop_remaining > 0:
                    self.drop_remaining -= 1
                    self.dropped_sends += 1
                    raise ConnectionResetError(
                        f"chaos: link {self.name} dropped frame "
                        f"({self.drop_remaining} drops remaining)")
                if self.latency_s > 0 or self.jitter_s > 0:
                    delay += self.latency_s
                    if self.jitter_s > 0:
                        delay += float(self._rng.random()) * self.jitter_s
                    self.delayed_sends += 1
                if self.throttle_bytes_per_s > 0 and nbytes > 0:
                    delay += nbytes / self.throttle_bytes_per_s
                    self.throttled_bytes += int(nbytes)
        if delay > 0:
            self._sleep(delay)

    def after_recv(self, req: Mapping[str, Any]) -> None:
        """In-path hook AFTER a response frame arrived. A one-way
        partition loses the RESPONSE: the peer applied the op, but the
        caller observes a connection error — a retry may duplicate the op
        (the at-least-once ack-loss window, dedup'd downstream)."""
        with self._lock:
            if self.partition_mode == "one_way" \
                    and _match_frame(self._partition_match, req):
                self.lost_responses += 1
                raise ConnectionError(
                    f"chaos: link {self.name} partitioned (one_way) — "
                    f"response lost")

    # ------------------------------------------------------------ snapshot
    def snapshot_entry(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "active": self.active(),
                "partition_mode": self.partition_mode,
                "windows_begun": self.windows_begun,
                "delayed_sends_total": self.delayed_sends,
                "dropped_sends_total": self.dropped_sends,
                "partitioned_sends_total": self.partitioned_sends,
                "lost_responses_total": self.lost_responses,
                "throttled_bytes_total": self.throttled_bytes,
            }


class LinkFaultPlane:
    """Registry of named links keyed by ``(role, peer)``.

    One plane per process; drills hand each framing client the link for
    its role, bind :class:`NetworkPartition` / :class:`LinkDegrade`
    injectors to :class:`ChaosPlan` windows against those links, and
    mirror :meth:`snapshot` through ``MetricsCollector.sync_netfaults``
    (optionally merged with the broker's fencing counters)."""

    def __init__(self, sleep: Optional[Callable[[float], None]] = None,
                 seed: int = 0):
        self._sleep = sleep
        self._seed = int(seed)
        self._links: Dict[tuple, LinkState] = {}
        self._lock = threading.Lock()

    def link(self, role: str, peer: str) -> LinkState:
        key = (str(role), str(peer))
        with self._lock:
            st = self._links.get(key)
            if st is None:
                st = LinkState(role, peer, sleep=self._sleep,
                               seed=self._seed)
                self._links[key] = st
            return st

    def links(self) -> List[LinkState]:
        with self._lock:
            return list(self._links.values())

    def snapshot(self, fencing: Optional[Mapping[str, Any]] = None,
                 ) -> Dict[str, Any]:
        """JSON-able state shaped for ``sync_netfaults``. ``fencing`` is
        an optional broker fence-counter block (``NetBrokerClient.
        status()`` / ``InMemoryBroker.producer_fence_stats()``)."""
        snap: Dict[str, Any] = {
            "links": {st.name: st.snapshot_entry()
                      for st in sorted(self.links(),
                                       key=lambda s: s.name)},
        }
        if fencing is not None:
            snap["fencing"] = {
                "fenced_produces_total":
                    int(fencing.get("fenced_produces", 0)),
                "fenced_commits_total":
                    int(fencing.get("fenced_commits", 0)),
            }
        return snap


# ---------------------------------------------------------------------------
# injectors (registered beside the PR 8 set in chaos.__init__)
# ---------------------------------------------------------------------------


class NetworkPartition:
    """Partition one or more links for the window.

    ``mode="full"`` — matched requests are refused at send (they never
    reach the peer); ``mode="one_way"`` — matched requests REACH the peer
    and are applied, but the responses are lost (ack-loss: a retrying
    producer duplicates, the documented at-least-once window). ``match``
    scopes the partition to an op/topic subset — a control-plane-only
    match is the asymmetric "deaf to the coordinator, data still flows"
    scenario."""

    def __init__(self, links: Sequence[LinkState], mode: str = "full",
                 match: Optional[Mapping[str, Any]] = None):
        if not links:
            raise ValueError("NetworkPartition needs >= 1 link")
        self.links = list(links)
        self.mode = mode
        self.match = dict(match) if match else None
        self.partitions = 0

    def begin(self, now: float) -> None:
        self.partitions += 1
        for link in self.links:
            link.set_partition(self.mode, self.match)

    def end(self, now: float) -> None:
        for link in self.links:
            link.clear_partition()


class LinkDegrade:
    """Degrade (never sever) one or more links for the window: added
    latency (+ seeded jitter), slow-link throttling (bytes/s), and/or a
    bounded run of dropped sends (drop-then-reconnect: the client's real
    reconnect path runs, then the link heals)."""

    def __init__(self, links: Sequence[LinkState], latency_s: float = 0.0,
                 jitter_s: float = 0.0, throttle_bytes_per_s: float = 0.0,
                 drop_next: int = 0,
                 match: Optional[Mapping[str, Any]] = None):
        if not links:
            raise ValueError("LinkDegrade needs >= 1 link")
        if latency_s <= 0 and jitter_s <= 0 and throttle_bytes_per_s <= 0 \
                and drop_next <= 0:
            raise ValueError("LinkDegrade needs at least one effect")
        self.links = list(links)
        self.latency_s = float(latency_s)
        self.jitter_s = float(jitter_s)
        self.throttle_bytes_per_s = float(throttle_bytes_per_s)
        self.drop_next = int(drop_next)
        self.match = dict(match) if match else None
        self.degrades = 0

    def begin(self, now: float) -> None:
        self.degrades += 1
        for link in self.links:
            link.set_degrade(latency_s=self.latency_s,
                             jitter_s=self.jitter_s,
                             throttle_bytes_per_s=self.throttle_bytes_per_s,
                             drop_next=self.drop_next, match=self.match)

    def end(self, now: float) -> None:
        for link in self.links:
            link.clear_degrade()


# ---------------------------------------------------------------------------
# schedule-driven link (the worker-process form)
# ---------------------------------------------------------------------------


class ScheduledLink:
    """A link whose fault windows advance on every frame.

    Worker processes cannot be reached by the drill coordinator once
    partitioned — so the schedule rides INTO the process (the worker
    spec) and the link polls its own :class:`ChaosPlan` on the injected
    clock before every frame. Until the clock has a base (the drill
    coordinator announces the shared epoch over the control topic before
    any window opens), the plan never begins."""

    def __init__(self, state: LinkState, plan: ChaosPlan,
                 clock: Callable[[], float]):
        self.state = state
        self.plan = plan
        self.clock = clock

    def _poll(self) -> None:
        now = self.clock()
        if now == now and now > float("-inf"):    # NaN/-inf = no epoch yet
            self.plan.poll(now)

    def before_send(self, req: Mapping[str, Any], nbytes: int = 0) -> None:
        self._poll()
        self.state.before_send(req, nbytes)

    def after_recv(self, req: Mapping[str, Any]) -> None:
        self._poll()
        self.state.after_recv(req)


def scheduled_link_from_spec(windows: Sequence[Mapping[str, Any]],
                             role: str, peer: str,
                             clock: Callable[[], float],
                             sleep: Optional[Callable[[float], None]] = None,
                             seed: int = 0) -> ScheduledLink:
    """Build a :class:`ScheduledLink` from JSON-able window dicts (the
    worker-spec wire form). Each window::

        {"name": ..., "kind": "partition"|"degrade",
         "t_start": ..., "t_end": ...,
         # partition: "mode" ("full"|"one_way"), optional "match"
         # degrade: "latency_s"/"jitter_s"/"throttle_bytes_per_s"/
         #          "drop_next", optional "match"
        }
    """
    state = LinkState(role, peer, sleep=sleep, seed=seed)
    fws = [FaultWindow(str(w["name"]), str(w["kind"]),
                       float(w["t_start"]), float(w["t_end"]))
           for w in windows]
    plan = ChaosPlan(fws)
    for w in windows:
        kind = str(w["kind"])
        if kind == "partition":
            inj: Any = NetworkPartition(
                [state], mode=str(w.get("mode", "full")),
                match=w.get("match"))
        elif kind == "degrade":
            inj = LinkDegrade(
                [state], latency_s=float(w.get("latency_s", 0.0)),
                jitter_s=float(w.get("jitter_s", 0.0)),
                throttle_bytes_per_s=float(
                    w.get("throttle_bytes_per_s", 0.0)),
                drop_next=int(w.get("drop_next", 0)),
                match=w.get("match"))
        else:
            raise ValueError(f"unknown netfault window kind {kind!r}")
        plan.bind(str(w["name"]), inj)
    return ScheduledLink(state, plan, clock)

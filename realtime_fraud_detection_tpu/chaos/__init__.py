"""Chaos plane: deterministic fault injection + adversarial scenarios.

Every other plane in this framework was proven by its own deterministic
drill *in isolation*; this package makes correlated failure a first-class,
replayable input. ``chaos.faults`` schedules named fault windows on the
drills' virtual clock and binds them to per-layer injectors (broker
replica outage, consumer-group member kill, device-replica death, slow
device, label stall, flash crowd); ``chaos.drill`` composes them — plus
the coordinated fraud ring from ``sim.fraud_patterns`` — into the
``rtfd chaos-drill`` combined recovery drill; ``chaos.netfaults``
degrades the NETWORK itself (named links in the framing transports'
request path: latency, throttle, bounded drops, one-way/full partitions
— the ``rtfd partition-drill`` substrate).
"""

from realtime_fraud_detection_tpu.chaos.faults import (
    BrokerReplicaOutage,
    ChaosPlan,
    ConsumerMemberKill,
    DeviceReplicaDeath,
    FaultWindow,
    LabelStall,
    SlowDevice,
    WorkerKill,
)
from realtime_fraud_detection_tpu.chaos.netfaults import (
    LinkDegrade,
    LinkFaultPlane,
    LinkState,
    NetworkPartition,
    ScheduledLink,
)

__all__ = [
    "BrokerReplicaOutage",
    "ChaosPlan",
    "ConsumerMemberKill",
    "DeviceReplicaDeath",
    "FaultWindow",
    "LabelStall",
    "LinkDegrade",
    "LinkFaultPlane",
    "LinkState",
    "NetworkPartition",
    "ScheduledLink",
    "SlowDevice",
    "WorkerKill",
]

"""Shared device-timing discipline for bench.py and tune_tpu.py.

Two rules, both learned the hard way on the tunneled TPU (round 4):

1. **Vary the input every timed call.** The relay serves a repeated
   identical computation from a result cache — the r3-era bench measured a
   physically impossible 1.1 ms blocked call this way. Timed callables
   take the iteration index so callers cycle pre-staged input variants.

2. **Never pull device->host before or between timed sections.** The first
   ``device_get``/``np.asarray`` on a device array permanently switches
   the tunnel into synchronous dispatch (~85 ms per call); only
   ``block_until_ready`` is safe inside timed code. Build input variants
   from HOST arrays and ``device_put`` them; defer all result pulls past
   the last timed section.
"""

from __future__ import annotations

import time
from typing import Callable, List


def time_blocked(fn: Callable[[int], object], iters: int) -> List[float]:
    """Per-call latency in seconds: block on each call before the next.

    ``fn(i)`` must produce a fresh computation per index (rule 1).
    """
    import jax

    jax.block_until_ready(fn(0))         # warm (compile already done)
    times = []
    for i in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(i + 1))
        times.append(time.perf_counter() - t0)
    return times


def throughput_pipelined(fn: Callable[[int], object], batch_size: int,
                         iters: int) -> float:
    """Items/second with async dispatch: the device stays fed, one block at
    the end. This is the number a local (non-tunneled) host observes, and
    the basis for honest MFU — no cache or dispatch artifact can inflate
    it. ``fn(i)`` varies per call (rule 1)."""
    import jax

    jax.block_until_ready(fn(0))
    t0 = time.perf_counter()
    outs = [fn(i + 1) for i in range(iters)]
    jax.block_until_ready(outs)
    return batch_size * iters / (time.perf_counter() - t0)

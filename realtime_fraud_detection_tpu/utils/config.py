"""Unified typed configuration tree.

The reference has three disjoint config systems that drift from one another
(SURVEY.md section 5.6: Java JobConfig.java, Python config.py + an unloaded
configs/models.json, simulator argparse; the k8s ConfigMap even ships
*different* ensemble weights). Here there is exactly one tree with layering:

    defaults -> JSON file (``Config.from_file``) -> env vars (``RTFD_*`` and
    the reference's own names) -> explicit kwargs / CLI.

Model registry semantics mirror reference config.py:126-199 (names, types,
weights, hyperparameters); ensemble thresholds mirror config.py:118-124 and
ensemble_predictor.py:344-369.
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List


VALID_STRATEGIES = ("weighted_average", "voting", "stacking")


def _env(name: str, default: str, *aliases: str) -> str:
    for key in (f"RTFD_{name}", name, *aliases):
        val = os.getenv(key)
        if val is not None:
            return val
    return default


@dataclass
class ModelConfig:
    """Per-model configuration (reference config.py:9-18)."""

    name: str
    model_type: str  # 'gbdt' | 'lstm' | 'bert' | 'gnn' | 'isolation_forest'
    weight: float = 1.0
    enabled: bool = True
    # reference parity field (config.py:13 per-model artifact path). Unused
    # by design here: all five branches live in ONE orbax checkpoint
    # (checkpoint.py) addressed by directory+step, not per-model files —
    # per-branch swaps go through set_models/per-branch validity instead.
    model_path: str = ""
    hyperparameters: Dict[str, Any] = field(default_factory=dict)


# Decision-ladder rung defaults (ensemble_predictor.py:344-356) — the ONE
# definition shared by EnsembleConfig, EnsembleParams, the compiled ladder
# (ensemble/combine.py) and its host-side twin (features/rules.py), so a
# default can't silently drift between them. Lives here because this module
# has no heavy deps and everything else already imports it.
DECLINE_THRESHOLD_DEFAULT = 0.95
REVIEW_THRESHOLD_DEFAULT = 0.8
MONITOR_THRESHOLD_DEFAULT = 0.6


@dataclass
class EnsembleConfig:
    """Ensemble strategy + decision thresholds (config.py:21-27)."""

    strategy: str = "weighted_average"  # weighted_average | voting | stacking
    confidence_threshold: float = 0.7
    fraud_threshold: float = 0.5
    enable_explanation: bool = True
    # Decision ladder (ensemble_predictor.py:344-356); validate() enforces
    # 0 <= monitor <= review <= decline <= 1 (a misordered ladder would
    # silently shadow rungs)
    decline_threshold: float = DECLINE_THRESHOLD_DEFAULT
    review_threshold: float = REVIEW_THRESHOLD_DEFAULT
    monitor_threshold: float = MONITOR_THRESHOLD_DEFAULT
    # Prediction cache (ensemble_predictor.py:57-58, 460-471)
    cache_ttl_seconds: float = 300.0
    cache_max_entries: int = 1000


# Branches whose params may take the sharded placement on the serving
# mesh (scoring/mesh_executor.py; parallel/layouts.SHARDABLE_BRANCHES maps
# these onto ScoringModels fields — a test pins the two in sync). Trees /
# iforest / rules are replicated by design.
MESH_SHARDABLE_BRANCHES = ("bert_text", "lstm_sequential", "graph_neural")


@dataclass
class MeshSettings:
    """Mesh geometry: the (data, model, seq) axes for core/mesh.py AND the
    GSPMD serving executor's knobs (scoring/mesh_executor.py).

    ``enabled`` opts a serving/stream deployment into mesh-sharded branch
    execution: ``replicas`` independent ``data x model`` meshes in
    round-robin rotation (pool x mesh — replicate the MESH, not the
    chip), each storing the ``shard_branches`` params sharded over
    ``model`` (per-chip HBM ~1/model) while the microbatch shards over
    ``data``. Off by default — the replicated DevicePool remains the
    baseline plane; ``rtfd mesh-drill`` gates the sharded path's
    bit-equality contract.
    """

    data: int | None = None
    model: int = 1
    seq: int = 1
    # serving executor (scoring/mesh_executor.py)
    enabled: bool = False
    replicas: int = 1
    inflight_depth: int = 2
    shard_branches: List[str] = field(
        default_factory=lambda: ["bert_text"])

    def validate(self) -> None:
        if self.model < 1 or self.seq < 1:
            raise ValueError(
                f"mesh axes must be >= 1, got model={self.model} "
                f"seq={self.seq}")
        if self.replicas < 1 or self.inflight_depth < 1:
            raise ValueError(
                "mesh.replicas and mesh.inflight_depth must be >= 1")
        bad = [b for b in self.shard_branches
               if b not in MESH_SHARDABLE_BRANCHES]
        if bad:
            raise ValueError(
                f"mesh.shard_branches {bad} not shardable; valid: "
                f"{list(MESH_SHARDABLE_BRANCHES)} (trees/iforest/rules "
                f"are replicated by design)")


@dataclass
class ServingConfig:
    """Scoring service settings (reference config.py:72-88 + TF-Serving
    batching config, k8s/manifests/ml-models-deployment.yaml:270-290)."""

    host: str = "0.0.0.0"
    port: int = 8080
    max_concurrent_predictions: int = 100
    prediction_timeout_seconds: float = 5.0
    batch_size_limit: int = 1000
    # Microbatcher: fixed-latency deadline + max batch
    microbatch_deadline_ms: float = 5.0
    microbatch_max_size: int = 256
    # Prediction TTL cache switch (reference ensemble_predictor.py:437-471),
    # keyed by transaction_id — idempotent retries of the same transaction
    # serve the cached §2.7 response without re-scoring. TTL/size come from
    # EnsembleConfig.cache_ttl_seconds / cache_max_entries (the reference
    # keeps the cache knobs on the ensemble config; one source of truth).
    enable_prediction_cache: bool = True
    # Two-phase pipelined microbatcher (serving/batcher.py): dispatch batch
    # N+1 (cache check + host assembly + device launch) while batch N's
    # finalize still waits on the device — host assembly overlaps device
    # compute. Results stay in per-request order; off by default so the
    # single-phase path remains the reproducible baseline. TRADEOFF: the
    # prediction cache's idempotent-retry window narrows — a retry of a
    # transaction arriving while its first copy is between dispatch and
    # finalize (the one-batch in-flight window, ~the device latency) misses
    # the cache and is scored + written back again (velocity counts that
    # transaction twice). The serial path closes that window by strict
    # put-before-next-lookup ordering.
    overlap_assembly: bool = False
    # Device-pool scoring (scoring/device_pool.py): replicate the model
    # onto every addressable device and dispatch whole microbatches
    # round-robin with per-replica in-flight depth. Implies the two-phase
    # pipelined microbatcher (overlap_assembly's machinery) with its
    # pipeline depth raised to the pool capacity, so the same
    # idempotent-retry-window tradeoff applies, widened to the pool's
    # in-flight window.
    device_pool: bool = False
    inflight_depth: int = 2
    # self-tuning host pipeline (tuning/): arrival-aware just-in-time
    # batch closing + the online config tuner drive the microbatcher's
    # close decisions instead of the fixed deadline. Knobs live in
    # Config.tuning (TuningSettings); this switch attaches the plane to
    # the serving path. Off = close decisions bit-identical to today.
    autotune: bool = False


@dataclass
class StreamConfig:
    """Transport settings (reference JobConfig.java:20-38 semantics)."""

    backend: str = "memory"  # memory | kafka
    bootstrap_servers: List[str] = field(default_factory=lambda: ["localhost:9092"])
    transactions_topic: str = "payment-transactions"
    enriched_topic: str = "transaction-enriched"
    features_topic: str = "transaction-features"
    predictions_topic: str = "fraud-predictions"
    alerts_topic: str = "fraud-alerts"
    alert_score_threshold: float = 0.7
    partitions: int = 12
    checkpoint_interval_ms: int = 10_000


@dataclass
class SimConfig:
    """Load-generator settings (reference simulator.py:480-489)."""

    tps: int = 100
    num_users: int = 10_000
    num_merchants: int = 5_000
    seed: int = 42


@dataclass
class MonitoringConfig:
    enable_prometheus: bool = True
    prometheus_port: int = 8081
    log_level: str = "INFO"
    # rotating JSON log file (reference logging_config.py file handler);
    # empty = console only. The service_name stamp rides the JSON lines.
    log_file: str = ""
    enable_performance_tracking: bool = True
    enable_drift_detection: bool = True


@dataclass
class QosSettings:
    """Deadline-aware QoS plane knobs (qos/): admission, budgets, ladder.

    Disabled by default — the plane is opt-in per deployment (``serve
    --qos``, ``run-job --qos``, or config/JSON overlay). All knobs are
    runtime state to the plane: changing them via ``POST /qos`` never
    recompiles anything.
    """

    enabled: bool = False
    # per-transaction latency budget (the p99 contract) and the slice of it
    # reserved for transfer+compute+return — assembly must close a batch
    # margin_ms before the oldest waiter's deadline
    budget_ms: float = 20.0
    assemble_margin_ms: float = 2.0
    # token-bucket admission: sustainable txn/s (0 = unlimited), bucket
    # size (0 = one second of tokens), and the reserve fraction under
    # which the low class sheds first
    admission_rate: float = 0.0
    admission_burst: float = 0.0
    low_reserve_frac: float = 0.25
    # priority classification by amount when the record carries no
    # explicit "priority" field: >= high_value_amount -> high (never
    # shed), < low_value_amount -> low (sheds first), else normal
    high_value_amount: float = 500.0
    low_value_amount: float = 25.0
    # degradation ladder (qos/ladder.py): backlog watermarks in records,
    # consecutive observations per step (the hysteresis)
    ladder_enabled: bool = True
    ladder_high_backlog: float = 2048.0
    ladder_low_backlog: float = 256.0
    ladder_patience: int = 2
    # recovery (step-up) patience; 0 = same as ladder_patience. Recovery
    # slower than degradation keeps a sustained overload from flapping the
    # ensemble (each recovery buys a fresh queueing spike)
    ladder_up_patience: int = 8

    def validate(self) -> None:
        """The QoS invariants — enforced at config load (Config.validate)
        AND on every runtime update (QosPlane.configure), so POST /qos can
        never put the plane into a state the loader would refuse."""
        if self.budget_ms <= 0 or self.assemble_margin_ms < 0 \
                or self.assemble_margin_ms >= self.budget_ms:
            raise ValueError(
                f"qos budget must satisfy 0 <= assemble_margin_ms < "
                f"budget_ms, got margin={self.assemble_margin_ms} "
                f"budget={self.budget_ms}")
        if self.ladder_low_backlog > self.ladder_high_backlog:
            # inverted watermarks would make the ladder step down and up
            # on the SAME backlog — the flapping hysteresis exists to
            # prevent
            raise ValueError(
                f"qos ladder watermarks must satisfy low_backlog <= "
                f"high_backlog, got low={self.ladder_low_backlog} "
                f"high={self.ladder_high_backlog}")


@dataclass
class TracingSettings:
    """End-to-end transaction tracing plane knobs (obs/tracing.py):
    flight recorder, critical-path analyzer, SLO burn-rate tracking.

    Disabled by default — the plane is opt-in per deployment (``serve
    --trace``, ``run-job --trace``, or config/JSON overlay) with a
    measured-no-op fast path when off (one ``is None`` branch per batch
    on the scoring paths; ``rtfd trace-drill`` pins the enabled-path
    overhead bound too). All knobs are host state; nothing recompiles.
    """

    enabled: bool = False
    # process identity stamped into minted trace ids and wire carriers
    # ("" = single-process id format): what keeps two workers' fresh
    # roots globally distinct when the coordinator stitches their rings
    origin: str = ""
    # flight recorder: ring of the most recent completed traces, plus the
    # slowest-N kept verbatim (the tail exemplars Chrome-trace export and
    # /latency/breakdown surface regardless of ring churn)
    ring_size: int = 4096
    slowest_n: int = 32
    # SLO objective: objective_frac of scored transactions complete under
    # objective_ms, evaluated over a fast and a slow window (the standard
    # multi-window burn-rate pair); bucket_s is the counting granularity
    slo_objective_ms: float = 20.0
    slo_objective_frac: float = 0.99
    slo_fast_window_s: float = 3600.0
    slo_slow_window_s: float = 21600.0
    slo_bucket_s: float = 60.0
    # QoS consultation: a fast-window burn rate above slo_burn_threshold
    # for slo_gate_patience consecutive observations engages an extra
    # degradation floor (>= ladder rung 1); recovery needs
    # slo_gate_up_patience consecutive under-threshold observations —
    # the same asymmetric hysteresis discipline as the backlog ladder
    slo_burn_threshold: float = 2.0
    slo_gate_patience: int = 3
    slo_gate_up_patience: int = 12

    def validate(self) -> None:
        if not 0.0 < self.slo_objective_frac < 1.0:
            raise ValueError(
                f"tracing.slo_objective_frac must be in (0, 1), got "
                f"{self.slo_objective_frac}")
        if self.slo_objective_ms <= 0 or self.ring_size < 16 \
                or self.slowest_n < 1:
            raise ValueError(
                "tracing requires slo_objective_ms > 0, ring_size >= 16 "
                "and slowest_n >= 1")
        if not (0 < self.slo_bucket_s <= self.slo_fast_window_s
                <= self.slo_slow_window_s):
            # a fast window longer than the slow one would invert the
            # burn-alerting pair; a bucket wider than the fast window
            # would make its burn rate a single stale cell
            raise ValueError(
                f"tracing SLO windows must satisfy 0 < bucket_s <= "
                f"fast_window_s <= slow_window_s, got "
                f"bucket={self.slo_bucket_s} fast={self.slo_fast_window_s} "
                f"slow={self.slo_slow_window_s}")
        if self.slo_burn_threshold <= 0 or self.slo_gate_patience < 1 \
                or self.slo_gate_up_patience < 1:
            raise ValueError(
                "tracing SLO gate requires burn_threshold > 0 and "
                "patience/up_patience >= 1")


@dataclass
class TuningSettings:
    """Self-tuning host pipeline knobs (tuning/): arrival-rate forecast,
    just-in-time batch closing, and the gradient-free online config tuner.

    Disabled by default — the plane is opt-in per deployment (``serve
    --autotune``, ``run-job --autotune``, or config/JSON overlay). With it
    off, batch-close decisions are BIT-IDENTICAL to the fixed-deadline
    path (the microbatchers take the controller branch only when one is
    attached). All knobs are host state; nothing recompiles.
    """

    enabled: bool = False
    # arrival forecaster (tuning/forecast.py): Holt double-exponential
    # smoothing over time-bucketed admission counts. bucket_s is the
    # counting granularity (and the forecast reaction time); alpha/beta
    # the level/trend smoothing factors
    forecast_bucket_s: float = 0.02
    forecast_alpha: float = 0.5
    forecast_beta: float = 0.2
    # just-in-time closer (tuning/controller.py): the tuned max-wait
    # deadline moves within [deadline_min_ms, deadline_max_ms]; with a
    # QoS plane configured, deadline_max_ms must leave the budget's
    # assembly slice intact (validated — the tuner can NEVER starve a
    # latency budget the QoS plane promised)
    deadline_min_ms: float = 0.25
    deadline_max_ms: float = 10.0
    # free-rider patience: waiting for one more (service-free, pad-riding)
    # txn is worth `patience_factor x T(bucket) / fill` of the current
    # waiters' time — the marginal-gain-vs-cost knob (arXiv:1904.07421)
    patience_factor: float = 1.0
    # candidate bucket sets the tuner may select among (index 0 is the
    # starting set). Each must be a non-empty ascending list of positive
    # sizes; the defaults are subsets of core/batching.BATCH_BUCKETS so a
    # tuned close boundary always lands on a compile-cached padded shape
    # (closing at an off-bucket size pads up and wastes the difference).
    bucket_sets: List[List[int]] = field(default_factory=lambda: [
        [1, 8, 32, 128, 256],
        [1, 32, 256],
        [1, 8, 32, 256],
    ])
    # online tuner (tuning/tuner.py): epoch length in completed batches,
    # the relative admitted-p99 improvement required to KEEP a move (the
    # hysteresis), and the post-move cooldown in epochs
    tune_interval_batches: int = 50
    hysteresis_frac: float = 0.05
    tuner_cooldown_epochs: int = 2
    # overlap / in-flight depth search range
    inflight_min: int = 1
    inflight_max: int = 4

    def clamp_to_qos(self, qos: "QosSettings | None") -> None:
        """Clamp the deadline search space to the QoS budget's assembly
        slice, then re-validate — the ONE clamp-then-check recipe the CLI
        entry points (`serve --autotune`, `run-job --autotune`) apply, so
        the floor rule can never diverge between them."""
        if qos is not None and getattr(qos, "enabled", False):
            limit = qos.budget_ms - qos.assemble_margin_ms
            self.deadline_max_ms = min(self.deadline_max_ms, limit)
            self.deadline_min_ms = min(self.deadline_min_ms,
                                       self.deadline_max_ms)
        self.validate(qos=qos)

    def validate(self, qos: "QosSettings | None" = None) -> None:
        if not (0.0 < self.deadline_min_ms <= self.deadline_max_ms):
            raise ValueError(
                f"tuning deadline bounds must satisfy 0 < deadline_min_ms "
                f"<= deadline_max_ms, got min={self.deadline_min_ms} "
                f"max={self.deadline_max_ms}")
        if not self.bucket_sets:
            raise ValueError("tuning.bucket_sets must not be empty")
        for bs in self.bucket_sets:
            if not bs or list(bs) != sorted(bs) or min(bs) < 1 \
                    or len(set(bs)) != len(bs):
                raise ValueError(
                    f"every tuning bucket set must be a non-empty strictly "
                    f"ascending list of positive sizes, got {bs!r}")
        if not (0.0 < self.forecast_alpha <= 1.0
                and 0.0 <= self.forecast_beta <= 1.0
                and self.forecast_bucket_s > 0):
            raise ValueError(
                "tuning forecast requires 0 < alpha <= 1, 0 <= beta <= 1 "
                "and bucket_s > 0")
        if self.tune_interval_batches < 1 or self.hysteresis_frac < 0 \
                or self.tuner_cooldown_epochs < 0:
            raise ValueError(
                "tuning requires tune_interval_batches >= 1, "
                "hysteresis_frac >= 0 and tuner_cooldown_epochs >= 0")
        if not (1 <= self.inflight_min <= self.inflight_max):
            raise ValueError(
                f"tuning requires 1 <= inflight_min <= inflight_max, got "
                f"min={self.inflight_min} max={self.inflight_max}")
        if self.patience_factor <= 0:
            raise ValueError("tuning.patience_factor must be > 0")
        if self.enabled and qos is not None \
                and getattr(qos, "enabled", False):
            # the hard QoS floor: the tuner's deadline search space may
            # never reach past the budget's assembly slice — a tuned
            # max-wait that outlives close_by would hold batches past the
            # deadline the QoS plane promised every admitted transaction.
            # Checked only when the plane is ON: a disabled tuner imposes
            # no constraint on an otherwise-valid QoS config.
            limit = qos.budget_ms - qos.assemble_margin_ms
            if self.deadline_max_ms > limit:
                raise ValueError(
                    f"tuning.deadline_max_ms={self.deadline_max_ms} "
                    f"violates the QoS budget: must be <= budget_ms - "
                    f"assemble_margin_ms = {limit}")


@dataclass
class FeedbackSettings:
    """Continuous-learning plane knobs (feedback/): label join, prequential
    evaluation, retrain policy, promotion gate. Disabled by default — the
    plane is opt-in per deployment (``serve``/``run-job --feedback``,
    config/JSON overlay). All knobs are host state: changing them never
    recompiles anything (a promoted blend that switches the combine
    STRATEGY recompiles once, like any strategy change).
    """

    enabled: bool = False
    # label-join windowing: how long an unlabeled prediction waits for its
    # chargeback before expiring, and the per-stream out-of-orderness
    label_horizon_s: float = 90 * 86_400.0
    label_ooo_s: float = 60.0
    pred_ooo_s: float = 5.0
    # hard cap on predictions waiting for a label (the watermark horizon
    # can't evict while the labels topic is silent; memory must not grow
    # with stream length)
    join_max_pending: int = 100_000
    # synthetic label emission (sim): compresses the chargeback delay
    # distribution (1.0 = realistic days; drills use tiny values)
    label_delay_scale: float = 1.0
    # labeled-example buffer (state/labeled.py)
    buffer_size: int = 50_000
    buffer_store_history: bool = False
    # prequential evaluation
    sliding_window: int = 2_000
    fading_gamma: float = 0.999
    operating_threshold: float = 0.5
    # retrain policy
    auc_drop: float = 0.08
    auc_floor: float = 0.0
    min_labels: int = 300
    cooldown_s: float = 600.0
    use_drift_trigger: bool = True
    # candidate training
    retrain_trees: int = 48
    retrain_depth: int = 5
    retrain_iforest_trees: int = 60
    retrain_neural: bool = False
    # promotion gate
    gate_holdout_frac: float = 0.2
    gate_select_frac: float = 0.2
    gate_min_positives: int = 12
    gate_auc_margin: float = 0.0
    gate_recall_tolerance: float = 0.02

    def validate(self) -> None:
        if not 0.0 < self.fading_gamma < 1.0:
            raise ValueError(
                f"feedback.fading_gamma must be in (0, 1), got "
                f"{self.fading_gamma}")
        if self.sliding_window < 10 or self.buffer_size < 10:
            raise ValueError(
                "feedback.sliding_window and buffer_size must be >= 10")
        if not (0.0 < self.gate_holdout_frac < 1.0
                and 0.0 < self.gate_select_frac < 1.0
                and self.gate_holdout_frac + self.gate_select_frac < 0.9):
            # the gate must always keep a real training majority: a split
            # that eats the training segment would gate candidates trained
            # on nothing
            raise ValueError(
                f"feedback gate fractions must satisfy 0 < holdout, select "
                f"and holdout + select < 0.9, got "
                f"holdout={self.gate_holdout_frac} "
                f"select={self.gate_select_frac}")
        if self.label_horizon_s <= 0 or self.label_delay_scale <= 0:
            raise ValueError(
                "feedback.label_horizon_s and label_delay_scale must be > 0")


@dataclass
class ChaosSettings:
    """Chaos plane knobs (chaos/): deterministic fault injection + the
    adversarial fraud-ring scenario, composed by ``rtfd chaos-drill``.

    Disabled by default — the plane exists for drills/tests/staging soaks,
    never wired into a hot path (injectors are explicit objects a harness
    constructs; production code paths carry no chaos branches). The knobs
    reach the drill via ``rtfd chaos-drill --config file.json``
    (``chaos.drill.apply_chaos_settings`` overlays them onto the drill
    config); all are virtual-clock quantities, so changing them reshapes
    the replayed timeline deterministically. ``enabled`` gates nothing
    today — it is the config-file switch a future always-on staging soak
    consults; the drill runs whenever invoked.
    """

    enabled: bool = False
    seed: int = 11
    # fault windows (virtual seconds, relative to their phase starts)
    broker_outage_s: float = 1.5       # replica down -> NotEnoughReplicas
    label_stall_s: float = 4.0         # label stream held back
    flash_crowd_mult: float = 2.5      # peak offered load / capacity
    flash_burst_mult: float = 1.6      # short bursts on top of the peak
    # adversarial fraud ring (sim/fraud_patterns.FraudRingConfig)
    ring_rate: float = 0.10
    ring_members: int = 24
    ring_merchants: int = 6
    ring_devices: int = 4
    ring_ips: int = 3
    # device-pool faults: how many in-flight fetches the dead replica
    # fails before revival, and the slow-device injected delay
    replica_faults: int = 1
    slow_device_ms: float = 40.0

    def validate(self) -> None:
        if self.broker_outage_s <= 0 or self.label_stall_s < 0:
            raise ValueError(
                "chaos.broker_outage_s must be > 0 and label_stall_s >= 0")
        if self.flash_crowd_mult < 1.0 or self.flash_burst_mult < 1.0:
            raise ValueError(
                f"chaos flash-crowd multipliers must be >= 1, got "
                f"crowd={self.flash_crowd_mult} "
                f"burst={self.flash_burst_mult}")
        if not 0.0 < self.ring_rate <= 1.0:
            raise ValueError(
                f"chaos.ring_rate must be in (0, 1], got {self.ring_rate}")
        if min(self.ring_members, self.ring_merchants, self.ring_devices,
               self.ring_ips) < 1:
            raise ValueError("chaos ring needs >= 1 of each entity kind")
        if self.replica_faults < 1 or self.slow_device_ms < 0:
            raise ValueError(
                "chaos.replica_faults must be >= 1 and slow_device_ms >= 0")


@dataclass
class ClusterSettings:
    """Partition-parallel worker plane knobs (cluster/): key-sharded
    state, checkpointed handoff, and the consistent-hash serving router.

    ``enabled`` turns on the serving-side router: this process serves
    ``/predict`` only for users whose partition the ring assigns to
    ``worker_id``; other keys answer 421 with the owning worker's
    address (``workers``), so a dumb HTTP client — or the ingress in
    front of the fleet — re-issues to the right shard. The
    partition↔worker placement is a pure function of (workers,
    n_partitions, virtual_nodes), identical in every process. The
    stream-side fleet (``cluster.fleet.WorkerFleet``) reads
    ``checkpoint_every`` for its handoff snapshot cadence.
    """

    enabled: bool = False
    # must match the transactions topic's partition count — the key →
    # partition hash is the transport's (stream/topics.py: 12)
    n_partitions: int = 12
    virtual_nodes: int = 256
    # completed batches between per-partition handoff snapshots
    # (round-robin over owned partitions; see ClusterWorker)
    checkpoint_every: int = 8
    # this process's identity in the ring ("" = not a fleet member)
    worker_id: str = ""
    # worker_id -> base URL, the router's redirect targets; the ring is
    # built over these ids
    workers: Dict[str, str] = field(default_factory=dict)
    # elastic process fleet (cluster/procfleet.py + cluster/autoscale.py):
    # worker-count bounds the autoscale controller moves between, the
    # capacity model it divides the forecast by, and the forecast lead
    # that lets the fleet grow BEFORE a diurnal peak (spawn latency is
    # paid inside the lead, not inside the latency budget)
    min_workers: int = 1
    max_workers: int = 8
    per_worker_tps: float = 200.0
    autoscale_headroom: float = 1.25
    autoscale_lead_s: float = 2.0
    autoscale_interval_s: float = 0.5
    autoscale_down_patience: int = 3

    def validate(self) -> None:
        if self.n_partitions < 1:
            raise ValueError(
                f"cluster.n_partitions must be >= 1, got "
                f"{self.n_partitions}")
        if self.virtual_nodes < 1 or self.checkpoint_every < 1:
            raise ValueError(
                "cluster.virtual_nodes and cluster.checkpoint_every "
                "must be >= 1")
        if not 1 <= self.min_workers <= self.max_workers:
            raise ValueError(
                f"cluster autoscale needs 1 <= min_workers <= "
                f"max_workers, got {self.min_workers}..{self.max_workers}")
        if (self.per_worker_tps <= 0 or self.autoscale_headroom < 1.0
                or self.autoscale_lead_s < 0
                or self.autoscale_interval_s <= 0
                or self.autoscale_down_patience < 1):
            raise ValueError(
                "cluster autoscale requires per_worker_tps > 0, "
                "headroom >= 1, lead_s >= 0, interval_s > 0 and "
                "down_patience >= 1")
        if self.enabled:
            if not self.workers:
                raise ValueError(
                    "cluster.enabled requires a non-empty cluster.workers "
                    "map (worker_id -> base URL)")
            if self.worker_id and self.worker_id not in self.workers:
                raise ValueError(
                    f"cluster.worker_id {self.worker_id!r} missing from "
                    f"cluster.workers {sorted(self.workers)}")


VALID_BERT_WEIGHTS = ("f32", "int8")
VALID_TREE_KERNELS = ("gather", "gemm")


@dataclass
class QuantSettings:
    """Quantized scoring plane knobs (models/quant.py + the GEMM-form tree
    kernels in models/trees.py): weight-only int8 for the BERT branch and
    contraction-form traversal for GBDT / isolation forest, selectable PER
    BRANCH.

    Disabled by default — the plane is opt-in per deployment (config/JSON
    overlay, or the bench/tune/soak ``--quant`` switches). Branch modes
    are STATIC arguments to the fused program: changing them recompiles
    once (like a combine-strategy change), then every microbatch runs the
    new kernel. The quality gate is ``rtfd quant-drill``: divergence below
    calibration noise, zero operating-point decision flips, AUC unchanged
    on the committed quality protocol — a mode that fails the drill has no
    business in a config file.
    """

    enabled: bool = False
    # BERT branch weights: "f32" (the baseline) or "int8" (weight-only
    # per-output-channel symmetric quantization, dequant-to-bf16 at the
    # matmul seam — ~4x smaller replicated params)
    bert_weights: str = "f32"
    # GBDT / isolation-forest traversal: "gather" (the D-step gather
    # oracle) or "gemm" (Hummingbird-style batched contractions)
    tree_kernel: str = "gather"
    iforest_kernel: str = "gather"

    def validate(self) -> None:
        if self.bert_weights not in VALID_BERT_WEIGHTS:
            raise ValueError(
                f"quant.bert_weights must be one of {VALID_BERT_WEIGHTS}, "
                f"got {self.bert_weights!r}")
        for name, kernel in (("tree_kernel", self.tree_kernel),
                             ("iforest_kernel", self.iforest_kernel)):
            if kernel not in VALID_TREE_KERNELS:
                raise ValueError(
                    f"quant.{name} must be one of {VALID_TREE_KERNELS}, "
                    f"got {kernel!r}")

    @classmethod
    def full(cls) -> "QuantSettings":
        """The everything-on preset behind the CLI/relay ``--quant``
        switches: weight-only int8 BERT + GEMM-form kernels for both tree
        branches — exactly the configuration ``rtfd quant-drill`` gates."""
        return cls(enabled=True, bert_weights="int8",
                   tree_kernel="gemm", iforest_kernel="gemm")

    def bert_mode(self) -> str:
        """The effective BERT weight mode ("f32" when the plane is off)."""
        return self.bert_weights if self.enabled else "f32"

    def stamp(self) -> Dict[str, str]:
        """The quantization-mode arch stamp: only the BERT weight form —
        the one mode that is a PARAMETER property (checkpoint.py derives
        the same key from saved pytrees via ``_derive_quant_mode`` and
        refuses silent cross-mode restores on it). The tree kernels are
        program selections, not checkpoint state, so they are
        deliberately absent."""
        return {"bert_weights": self.bert_mode()}


VALID_KERNEL_SITES = ("dequant_matmul", "epilogue", "attention",
                      "megakernel")
VALID_KERNEL_MODES = ("off", "pallas")
VALID_ATTENTION_KERNELS = ("reference", "flash")


@dataclass
class KernelSettings:
    """Hand-written Pallas kernel plane (ops/): per-site kernel selection
    for the fused scoring program.

    Three sites, selectable independently (the quant-plane discipline:
    structural detection where possible, static program selection
    otherwise, no recompile-on-swap surprises):

    - ``dequant_matmul``: the int8 BERT branch's fused dequant-matmul
      (ops/dequant_matmul.py) — the i8 -> compute-dtype widen happens in
      VMEM inside the kernel instead of trusting XLA to fuse the
      ``(i8 -> bf16) * scale`` weight read. Only engages where the params
      actually carry the weight-only int8 layout (models/quant.py); f32
      sites keep the plain matmul.
    - ``epilogue``: the fused score-and-blend epilogue (ops/epilogue.py)
      — branch predictions, branch-validity/QoS masks, blend weights and
      the decision/risk ladders combine on-chip, and the packed result
      matrix grows the per-model contribution + rules-only ladder columns
      so ``FraudScorer.finalize`` does pure column reads instead of
      per-record host blend math.
    - ``attention``: flash (blockwise Pallas) vs reference attention for
      the text encoder — the default flip is DRIVEN by the tune_tpu.py
      sweep, never hardcoded.

    Off by default: the plane is opt-in (config/JSON overlay, or the
    bench/tune/soak ``--kernels`` switches) until the TPU relay window
    proves the MXU bet. Kernel selection is RUNTIME config — never
    serialized into checkpoints, never part of the arch stamp — and the
    modes are STATIC arguments to the fused program (changing them
    recompiles once, like a quant kernel change). On hosts without a TPU
    the kernels run through the Pallas interpreter, pinned against the
    XLA reference by ``rtfd kernel-drill``.
    """

    enabled: bool = False
    dequant_matmul: str = "off"     # off | pallas
    epilogue: str = "off"           # off | pallas
    attention: str = "reference"    # reference | flash
    # the persistent whole-microbatch program (ops/megakernel.py). When it
    # engages it SUBSUMES the three per-site kernels above: one Pallas
    # program scores the batch end-to-end, and the per-site selections
    # only matter on shapes the megakernel declines (mega_supported),
    # which fall back to the per-site chain with honest fallback counts.
    megakernel: str = "off"         # off | pallas

    def validate(self) -> None:
        for name, mode in (("dequant_matmul", self.dequant_matmul),
                           ("epilogue", self.epilogue),
                           ("megakernel", self.megakernel)):
            if mode not in VALID_KERNEL_MODES:
                raise ValueError(
                    f"kernels.{name} must be one of {VALID_KERNEL_MODES}, "
                    f"got {mode!r}")
        if self.attention not in VALID_ATTENTION_KERNELS:
            raise ValueError(
                f"kernels.attention must be one of "
                f"{VALID_ATTENTION_KERNELS}, got {self.attention!r}")

    @classmethod
    def full(cls) -> "KernelSettings":
        """The everything-on preset behind the CLI/relay ``--kernels``
        switches: fused dequant-matmul + fused epilogue + flash attention
        — exactly the configuration ``rtfd kernel-drill`` gates."""
        return cls(enabled=True, dequant_matmul="pallas",
                   epilogue="pallas", attention="flash")

    @classmethod
    def mega(cls) -> "KernelSettings":
        """The ``--kernels --mega`` preset: the persistent megakernel on
        top of the full per-site plane, which remains the fallback path
        for shapes ``mega_supported`` declines (bucket 1, two-hop graph
        batches, VMEM-oversized param sets)."""
        return cls(enabled=True, dequant_matmul="pallas",
                   epilogue="pallas", attention="flash",
                   megakernel="pallas")

    def site_modes(self) -> Dict[str, str]:
        """Effective per-site modes (everything off while disabled) —
        the shape ``FraudScorer.kernel_snapshot`` and the kernel_*
        Prometheus series report."""
        if not self.enabled:
            return {"dequant_matmul": "off", "epilogue": "off",
                    "attention": "reference", "megakernel": "off"}
        return {"dequant_matmul": self.dequant_matmul,
                "epilogue": self.epilogue,
                "attention": self.attention,
                "megakernel": self.megakernel}


@dataclass
class StateConfig:
    """Windowed state store settings (RedisService.java key TTLs)."""

    backend: str = "memory"  # memory | redis
    redis_host: str = "localhost"
    redis_port: int = 6379
    transaction_ttl_s: int = 24 * 3600
    features_ttl_s: int = 2 * 3600
    # NOTE deliberately no velocity TTL knob: velocity keys expire at their
    # own window period by design (state/shared.py — this FIXES the
    # reference's uniform 1h TTL, which let a 24h velocity hash die early)
    user_history_len: int = 100  # RedisService.java:296-306 last-100 list
    merchant_history_len: int = 500


def _default_models() -> Dict[str, ModelConfig]:
    """The 5-model registry (reference config.py:126-199)."""
    return {
        "xgboost_primary": ModelConfig(
            name="xgboost_primary",
            model_type="gbdt",
            weight=0.40,
            hyperparameters={
                "n_estimators": 100,
                "max_depth": 6,
                "learning_rate": 0.1,
                "subsample": 0.8,
                "colsample_bytree": 0.8,
            },
        ),
        "lstm_sequential": ModelConfig(
            name="lstm_sequential",
            model_type="lstm",
            weight=0.25,
            hyperparameters={
                "sequence_length": 10,
                "hidden_units": 128,
                "dropout": 0.2,
            },
        ),
        "bert_text": ModelConfig(
            name="bert_text",
            model_type="bert",
            weight=0.15,
            hyperparameters={
                "max_length": 128,  # reference uses 512 but its texts are <64 tokens
                "vocab_size": 30522,
                "hidden_size": 768,
                "num_layers": 6,
                "num_heads": 12,
                "intermediate_size": 3072,
            },
        ),
        "graph_neural": ModelConfig(
            name="graph_neural",
            model_type="gnn",
            weight=0.15,
            hyperparameters={
                "hidden_channels": 64,
                "num_layers": 3,
                "dropout": 0.1,
                "num_neighbors": 16,
            },
        ),
        "isolation_forest": ModelConfig(
            name="isolation_forest",
            model_type="isolation_forest",
            weight=0.05,
            hyperparameters={
                "contamination": 0.1,
                "n_estimators": 100,
                "random_state": 42,
            },
        ),
    }


# Confidence multipliers per model (ensemble_predictor.py:331-337).
MODEL_CONFIDENCE_MULTIPLIER: Dict[str, float] = {
    "xgboost_primary": 1.0,
    "lstm_sequential": 0.8,
    "bert_text": 0.7,
    "graph_neural": 0.6,
    "isolation_forest": 0.5,
}
DEFAULT_CONFIDENCE_MULTIPLIER = 0.5


@dataclass
class Config:
    """Root configuration."""

    service_name: str = "rtfd-tpu"
    environment: str = "development"
    models_base_path: str = "artifacts/models"
    models: Dict[str, ModelConfig] = field(default_factory=_default_models)
    ensemble: EnsembleConfig = field(default_factory=EnsembleConfig)
    mesh: MeshSettings = field(default_factory=MeshSettings)
    serving: ServingConfig = field(default_factory=ServingConfig)
    stream: StreamConfig = field(default_factory=StreamConfig)
    state: StateConfig = field(default_factory=StateConfig)
    sim: SimConfig = field(default_factory=SimConfig)
    monitoring: MonitoringConfig = field(default_factory=MonitoringConfig)
    qos: QosSettings = field(default_factory=QosSettings)
    feedback: FeedbackSettings = field(default_factory=FeedbackSettings)
    tracing: TracingSettings = field(default_factory=TracingSettings)
    tuning: TuningSettings = field(default_factory=TuningSettings)
    chaos: ChaosSettings = field(default_factory=ChaosSettings)
    quant: QuantSettings = field(default_factory=QuantSettings)
    cluster: ClusterSettings = field(default_factory=ClusterSettings)
    kernels: KernelSettings = field(default_factory=KernelSettings)

    def __post_init__(self) -> None:
        self._apply_env()
        self.validate()

    # -- env layering ------------------------------------------------------
    def _apply_env(self) -> None:
        self.models_base_path = _env("MODELS_PATH", self.models_base_path)
        self.serving.port = int(_env("ML_SERVICE_PORT", str(self.serving.port)))
        self.serving.host = _env("ML_SERVICE_HOST", self.serving.host)
        self.ensemble.strategy = _env("ENSEMBLE_STRATEGY", self.ensemble.strategy)
        self.ensemble.confidence_threshold = float(
            _env("CONFIDENCE_THRESHOLD", str(self.ensemble.confidence_threshold))
        )
        self.ensemble.fraud_threshold = float(
            _env("FRAUD_THRESHOLD", str(self.ensemble.fraud_threshold))
        )
        self.monitoring.log_level = _env("LOG_LEVEL", self.monitoring.log_level)
        self.monitoring.log_file = _env("LOG_FILE", self.monitoring.log_file)
        # the reference's Redis env contract (config.py REDIS_HOST/PORT):
        # with state.backend="redis" these select the shared state plane
        self.state.backend = _env("RTFD_STATE_BACKEND", self.state.backend)
        self.state.redis_host = _env("REDIS_HOST", self.state.redis_host)
        self.state.redis_port = int(
            _env("REDIS_PORT", str(self.state.redis_port)))

    # -- registry helpers (reference config.py:201-224) --------------------
    def get_model_config(self, model_name: str) -> ModelConfig:
        if model_name not in self.models:
            raise ValueError(f"Model '{model_name}' not found in configuration")
        return self.models[model_name]

    def get_enabled_models(self) -> Dict[str, ModelConfig]:
        return {n: c for n, c in self.models.items() if c.enabled}

    def normalized_weights(self) -> Dict[str, float]:
        enabled = self.get_enabled_models()
        total = sum(c.weight for c in enabled.values())
        if total <= 0:
            return {n: 0.0 for n in enabled}
        return {n: c.weight / total for n, c in enabled.items()}

    def update_model_weight(self, model_name: str, weight: float) -> None:
        if model_name in self.models:
            self.models[model_name].weight = weight

    def disable_model(self, model_name: str) -> None:
        if model_name in self.models:
            self.models[model_name].enabled = False

    def enable_model(self, model_name: str) -> None:
        if model_name in self.models:
            self.models[model_name].enabled = True

    @staticmethod
    def load_selected_blend_weights(artifact_path: str) -> Dict[str, float]:
        """Parse a quality-eval artifact's ``selected_blend.weights`` —
        the ONE place the artifact schema is read (apply_quality_artifact
        and the A/B canary both call it). Malformed shapes raise
        ValueError, never AttributeError."""
        with open(artifact_path) as f:
            artifact = json.load(f)
        blend = (artifact.get("selected_blend")
                 if isinstance(artifact, dict) else None)
        weights = blend.get("weights") if isinstance(blend, dict) else None
        if not isinstance(weights, dict) or not weights:
            raise ValueError(
                f"{artifact_path} has no selected_blend.weights — not a "
                f"quality-eval artifact?")
        return {str(n): float(w) for n, w in weights.items()}

    @staticmethod
    def load_selected_blend_strategy(artifact_path: str) -> str | None:
        """The artifact's measured combine strategy (selected_blend.
        strategy), or None for pre-strategy artifacts (which were all
        measured under weighted_average). Unknown names raise — a typo'd
        strategy must not silently serve the default."""
        with open(artifact_path) as f:
            artifact = json.load(f)
        blend = (artifact.get("selected_blend")
                 if isinstance(artifact, dict) else None)
        strategy = blend.get("strategy") if isinstance(blend, dict) else None
        if strategy is None:
            return None
        if strategy not in VALID_STRATEGIES:
            raise ValueError(
                f"{artifact_path} selected_blend.strategy {strategy!r} not "
                f"one of {VALID_STRATEGIES}")
        return str(strategy)

    @staticmethod
    def load_artifact_text_model(artifact_path: str) -> Dict[str, Any] | None:
        """The artifact's recorded text-encoder architecture
        (protocol.text_model: layers/width/vocab), or None when absent.
        The one place the key is read — serve/--quality-artifact and
        /reload-models both use it to refuse mixing artifacts and
        checkpoints from different architectures."""
        with open(artifact_path) as f:
            artifact = json.load(f)
        proto = (artifact.get("protocol")
                 if isinstance(artifact, dict) else None)
        tm = proto.get("text_model") if isinstance(proto, dict) else None
        return dict(tm) if isinstance(tm, dict) else None

    def apply_quality_artifact(self, artifact_path: str) -> Dict[str, float]:
        """Deploy a measured blend: set enabled models + weights from a
        quality-eval artifact (`rtfd quality-eval` / QUALITY_r*.json).

        This closes the loop from measurement to serving: the artifact's
        ``selected_blend`` — the branch set that survived the validation
        A/B gate, at its admitted weights — becomes this config's model
        table, so the scorer's validity mask and the device combine's
        weights are exactly what the protocol measured. Branches outside
        the blend stay configured but disabled (hot-enable later via
        /reload-models + enable_model without a recompile). When the
        artifact records a measured combine strategy (selected_blend.
        strategy — e.g. the stacked combiner), that deploys too (NOTE: a
        strategy change is the one blend knob that recompiles the fused
        program once, being a static argument). Returns the applied
        weights."""
        weights = self.load_selected_blend_weights(artifact_path)
        strategy = self.load_selected_blend_strategy(artifact_path)
        unknown = [n for n in weights if n not in self.models]
        if unknown:
            raise ValueError(
                f"artifact names unknown model(s) {unknown}; "
                f"configured: {sorted(self.models)}")
        for name, mc in self.models.items():
            if name in weights:
                mc.enabled = True
                mc.weight = float(weights[name])
            else:
                mc.enabled = False
        if strategy is not None:
            self.ensemble.strategy = strategy
        return {n: float(w) for n, w in weights.items()}

    # -- serialization -----------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_file(cls, config_path: str) -> "Config":
        with open(config_path) as f:
            data = json.load(f)
        return cls.from_dict(data)

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Config":
        cfg = cls()
        _merge_dataclass(cfg, data)
        # env re-applies AFTER the file overlay: defaults -> file -> env
        cfg._apply_env()
        cfg.validate()
        return cfg

    def validate(self) -> None:
        if self.ensemble.strategy not in VALID_STRATEGIES:
            raise ValueError(
                f"ensemble.strategy (env RTFD_ENSEMBLE_STRATEGY) must be one of "
                f"{VALID_STRATEGIES}, got {self.ensemble.strategy!r}"
            )
        e = self.ensemble
        if not (0.0 <= e.monitor_threshold <= e.review_threshold
                <= e.decline_threshold <= 1.0):
            # a misordered ladder silently shadows rungs (e.g. review 0.4 <
            # monitor 0.6 makes APPROVE_WITH_MONITORING unreachable) —
            # refuse it loudly, this is a fraud-decision path
            raise ValueError(
                "decision ladder must satisfy 0 <= monitor_threshold <= "
                "review_threshold <= decline_threshold <= 1, got "
                f"monitor={e.monitor_threshold} review={e.review_threshold} "
                f"decline={e.decline_threshold}")
        self.mesh.validate()
        self.qos.validate()
        self.feedback.validate()
        self.tracing.validate()
        self.tuning.validate(qos=self.qos)
        self.chaos.validate()
        self.quant.validate()
        self.cluster.validate()
        self.kernels.validate()


def _merge_dataclass(obj: Any, data: Dict[str, Any]) -> None:
    """Recursively overlay a dict onto a dataclass tree.

    Unknown keys WARN instead of silently vanishing: a typo'd or renamed
    knob in a config file must not quietly leave the default in force
    (e.g. a stale cache-TTL key silently serving cached fraud verdicts 10x
    longer than the operator configured).
    """
    import logging

    for key, value in data.items():
        if not hasattr(obj, key):
            logging.getLogger(__name__).warning(
                "config: unknown key %r on %s — ignored (typo or renamed "
                "knob?)", key, type(obj).__name__)
            continue
        current = getattr(obj, key)
        if dataclasses.is_dataclass(current) and isinstance(value, dict):
            _merge_dataclass(current, value)
        elif key == "models" and isinstance(value, dict):
            for model_name, model_data in value.items():
                if model_name in current and isinstance(model_data, dict):
                    for attr, v in model_data.items():
                        if hasattr(current[model_name], attr):
                            setattr(current[model_name], attr, v)
                elif isinstance(model_data, dict) and "model_type" in model_data:
                    current[model_name] = ModelConfig(
                        name=model_name, **{k: v for k, v in model_data.items() if k != "name"}
                    )
        else:
            setattr(obj, key, value)

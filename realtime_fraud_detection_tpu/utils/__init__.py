from realtime_fraud_detection_tpu.utils.config import (  # noqa: F401
    Config,
    ModelConfig,
    EnsembleConfig,
    ServingConfig,
    StreamConfig,
    SimConfig,
    MonitoringConfig,
    MeshSettings,
)

"""Bounded exponential backoff with deterministic jitter, behind a seam.

Every retry loop in the transports used to carry its own fixed
``time.sleep`` — two of them deliberately *under a lock* (the idempotent
producer's partition lock, the consumer-group rejoin under the membership
lock), justified by PR-7 lint pragmas because the sleeps were load-bearing
but untestable: no way to replay them on a drill's virtual clock, no
jitter, no bound.

:class:`DeterministicBackoff` replaces those sites:

- **bounded exponential**: ``base_s * mult**attempt`` capped at ``max_s``
  — a broker that stays down costs bounded per-attempt waits, never an
  unbounded doubling;
- **deterministic jitter**: the jitter fraction for attempt *k* is drawn
  from ``crc32(f"{seed}:{k}")`` — stable across processes and replays
  (``hash()`` is salted per process), so two runs of a seeded drill wait
  identical schedules while two *producers* with different seeds still
  de-synchronize their retry storms (the point of jitter);
- **injected sleep seam**: the chaos plane and the unit tests pass a
  recording / virtual-clock ``sleep`` so retry behavior is assertable
  without wall time. Production callers default to ``time.sleep``.
"""

from __future__ import annotations

import itertools
import os
import time
import zlib
from collections import deque
from typing import Callable, Optional

__all__ = ["DeterministicBackoff", "instance_seed"]

_INSTANCE_COUNTER = itertools.count()


def instance_seed(tag: str) -> int:
    """Backoff seed for one retrying INSTANCE: mixes the caller's tag with
    the process id and a per-process construction counter. The peers that
    must de-correlate their retry storms are exactly the ones that share a
    tag (every member of one consumer group, every client of one broker
    port) — a tag-only seed would hand the whole herd one identical
    schedule. Per-instance seeds keep them apart, while a seeded drill
    still constructs its instances in a deterministic order (and nothing
    in a drill's replay digest reads wall-clock retry delays)."""
    return zlib.crc32(
        f"{tag}:{os.getpid()}:{next(_INSTANCE_COUNTER)}".encode())


class DeterministicBackoff:
    """Retry-delay policy: ``delay(k)`` is pure, ``sleep(k)`` applies it."""

    def __init__(self, base_s: float = 0.05, mult: float = 2.0,
                 max_s: float = 1.0, jitter_frac: float = 0.25,
                 seed: int = 0,
                 sleep: Optional[Callable[[float], None]] = None):
        if base_s <= 0 or mult < 1.0 or max_s < base_s:
            raise ValueError(
                f"backoff requires base_s > 0, mult >= 1 and max_s >= "
                f"base_s, got base={base_s} mult={mult} max={max_s}")
        if not 0.0 <= jitter_frac <= 1.0:
            raise ValueError(
                f"jitter_frac must be in [0, 1], got {jitter_frac}")
        self.base_s = float(base_s)
        self.mult = float(mult)
        self.max_s = float(max_s)
        self.jitter_frac = float(jitter_frac)
        self.seed = int(seed)
        self._sleep = sleep if sleep is not None else time.sleep
        # applied delays (test/chaos ledger) — bounded: the instance lives
        # inside long-lived transports, and a flapping broker must not
        # grow an unbounded list for the process lifetime
        self.slept: deque = deque(maxlen=64)

    def delay(self, attempt: int) -> float:
        """Delay for the ``attempt``-th retry (0-based). Pure function of
        (config, seed, attempt) — replays bit-identically."""
        raw = min(self.max_s, self.base_s * self.mult ** max(0, int(attempt)))
        if self.jitter_frac <= 0.0:
            return raw
        # deterministic per-(seed, attempt) fraction in [0, 1): crc32 is
        # stable across processes, unlike salted str.__hash__
        frac = (zlib.crc32(f"{self.seed}:{int(attempt)}".encode())
                % 10_000) / 10_000.0
        # jitter shrinks the delay (decorrelates retry storms without ever
        # exceeding the bounded schedule)
        return raw * (1.0 - self.jitter_frac * frac)

    def sleep(self, attempt: int) -> float:
        """Apply the delay for ``attempt`` through the injected seam.
        Returns the delay actually requested (the test/chaos ledger gets a
        copy in ``slept``)."""
        d = self.delay(attempt)
        self.slept.append(d)
        self._sleep(d)
        return d

from realtime_fraud_detection_tpu.ensemble.combine import (  # noqa: F401
    STRATEGIES,
    WEIGHTED_AVERAGE,
    VOTING,
    STACKING,
    EnsembleParams,
    combine_predictions,
    model_confidence,
    ensemble_decision,
)

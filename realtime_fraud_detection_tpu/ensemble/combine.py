"""Ensemble combination: strategies, confidence, decisions — vectorized.

Mirror of ``EnsemblePredictor``'s math (ensemble_predictor.py:252-369), as a
single jittable function over a (B, M) prediction matrix instead of
per-request Python loops. Model failure tolerance (ensemble_predictor.py:
175-182 — a failed model is skipped and the rest renormalize) becomes a
``valid`` mask.

The three strategies (:254-323):
- weighted_average: sum(w*p)/sum(w)
- voting: fraction of models with p > fraud_threshold
- stacking: confidence-weighted average, falling back to weighted_average
  when total confidence is 0.

Per-model confidence (:325-342): min(1, 2*|p-0.5| * multiplier) with the
multipliers from config (utils/config.py MODEL_CONFIDENCE_MULTIPLIER).

Decision ladder (:344-356): low confidence -> REVIEW; p>=0.95 DECLINE;
>=0.8 REVIEW; >=0.6 APPROVE_WITH_MONITORING; else APPROVE.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Sequence

import jax
import jax.numpy as jnp
from flax import struct

from realtime_fraud_detection_tpu.features.rules import (
    APPROVE,
    APPROVE_WITH_MONITORING,
    DECLINE,
    DECLINE_THRESHOLD_DEFAULT,
    MONITOR_THRESHOLD_DEFAULT,
    REVIEW,
    REVIEW_THRESHOLD_DEFAULT,
    risk_level_code,
)
from realtime_fraud_detection_tpu.utils.config import (
    Config,
    DEFAULT_CONFIDENCE_MULTIPLIER,
    MODEL_CONFIDENCE_MULTIPLIER,
    VALID_STRATEGIES,
)

# single source of truth lives in utils.config (Config.validate checks it)
STRATEGIES: tuple[str, ...] = VALID_STRATEGIES
WEIGHTED_AVERAGE, VOTING, STACKING = range(3)


@struct.dataclass
class EnsembleParams:
    """Static ensemble parameters as arrays over the model axis."""

    weights: jax.Array               # f32[M] (normalized over enabled models)
    confidence_multipliers: jax.Array  # f32[M]
    strategy: int = struct.field(pytree_node=False, default=WEIGHTED_AVERAGE)
    fraud_threshold: float = struct.field(pytree_node=False, default=0.5)
    confidence_threshold: float = struct.field(pytree_node=False, default=0.7)
    # decision-ladder rungs (ensemble_predictor.py:344-356; configurable in
    # the reference's EnsembleConfig) — static so XLA folds them into the
    # compiled ladder; changing them recompiles, like any threshold change.
    # Defaults come from the one shared definition in features/rules.py.
    decline_threshold: float = struct.field(
        pytree_node=False, default=DECLINE_THRESHOLD_DEFAULT)
    review_threshold: float = struct.field(
        pytree_node=False, default=REVIEW_THRESHOLD_DEFAULT)
    monitor_threshold: float = struct.field(
        pytree_node=False, default=MONITOR_THRESHOLD_DEFAULT)

    @classmethod
    def from_config(cls, config: Config, model_names: Sequence[str]) -> "EnsembleParams":
        norm = config.normalized_weights()
        weights = jnp.asarray([norm.get(n, 0.0) for n in model_names], jnp.float32)
        mults = jnp.asarray(
            [MODEL_CONFIDENCE_MULTIPLIER.get(n, DEFAULT_CONFIDENCE_MULTIPLIER)
             for n in model_names],
            jnp.float32,
        )
        return cls(
            weights=weights,
            confidence_multipliers=mults,
            strategy=STRATEGIES.index(config.ensemble.strategy),
            fraud_threshold=config.ensemble.fraud_threshold,
            confidence_threshold=config.ensemble.confidence_threshold,
            decline_threshold=config.ensemble.decline_threshold,
            review_threshold=config.ensemble.review_threshold,
            monitor_threshold=config.ensemble.monitor_threshold,
        )


def model_confidence(preds: jax.Array, multipliers: jax.Array) -> jax.Array:
    """Per-model confidence (ensemble_predictor.py:325-342). (B,M)->(B,M)."""
    return jnp.minimum(1.0, jnp.abs(preds - 0.5) * 2.0 * multipliers[None, :])


@partial(jax.jit, static_argnames=("with_confidences",))
def combine_predictions(
    preds: jax.Array,            # f32[B, M] per-model fraud probabilities
    valid: jax.Array,            # bool[B, M] or bool[M] — failed models masked
    params: EnsembleParams,
    with_confidences: bool = True,
) -> Dict[str, jax.Array]:
    """Combine per-model predictions into the final scoring outputs."""
    if valid.ndim == 1:
        valid = jnp.broadcast_to(valid[None, :], preds.shape)
    vf = valid.astype(jnp.float32)

    conf = model_confidence(preds, params.confidence_multipliers) * vf
    w = params.weights[None, :] * vf

    # weighted average (:263-284)
    w_total = w.sum(axis=1)
    wa_prob = jnp.where(w_total > 0, (preds * w).sum(axis=1) / jnp.maximum(w_total, 1e-12), 0.5)
    wa_conf = jnp.where(w_total > 0, (conf * w).sum(axis=1) / jnp.maximum(w_total, 1e-12), 0.0)

    # voting (:286-303)
    n_valid = vf.sum(axis=1)
    votes = ((preds > params.fraud_threshold) & valid).sum(axis=1)
    vote_prob = jnp.where(n_valid > 0, votes / jnp.maximum(n_valid, 1.0), 0.0)
    vote_conf = jnp.where(n_valid > 0, conf.sum(axis=1) / jnp.maximum(n_valid, 1.0), 0.0)

    # stacking (:305-323)
    conf_total = conf.sum(axis=1)
    stack_prob = jnp.where(
        conf_total > 0, (preds * conf).sum(axis=1) / jnp.maximum(conf_total, 1e-12), wa_prob
    )
    stack_conf = jnp.where(
        conf_total > 0, conf_total / jnp.maximum(n_valid, 1.0), wa_conf
    )

    if params.strategy == WEIGHTED_AVERAGE:
        prob, confidence = wa_prob, wa_conf
    elif params.strategy == VOTING:
        prob, confidence = vote_prob, vote_conf
    else:
        prob, confidence = stack_prob, stack_conf

    decision = ensemble_decision(
        prob, confidence, params.confidence_threshold,
        decline=params.decline_threshold, review=params.review_threshold,
        monitor=params.monitor_threshold)
    out = {
        "fraud_probability": prob,
        "confidence": confidence,
        "decision": decision,
        "risk_level": risk_level_code(prob),
    }
    if with_confidences:
        out["model_confidences"] = conf
    return out


def blend_branch_scores(
    scores_by_branch: Dict[str, "object"],
    weights_by_name: Dict[str, float],
    strategy: str = "weighted_average",
):
    """Host-side serving-parity blend over NAMED branch score arrays.

    The ONE recipe shared by the offline protocol (training/blend_eval.py)
    and the continuous-learning gate (feedback/policy.py): branch scores
    are laid out in MODEL_NAMES order, weights map onto EnsembleParams,
    validity = (weight > 0 AND the branch produced scores), and the SAME
    jitted ``combine_predictions`` the fused device program runs does the
    math — at any strategy, including the stacked combiner. Returns the
    fraud-probability vector as a NumPy array.
    """
    import numpy as np

    from realtime_fraud_detection_tpu.scoring import MODEL_NAMES

    if strategy not in STRATEGIES:
        raise ValueError(
            f"strategy must be one of {STRATEGIES}, got {strategy!r}")
    base = EnsembleParams.from_config(Config(), list(MODEL_NAMES))
    w = jnp.asarray([float(weights_by_name.get(n, 0.0))
                     for n in MODEL_NAMES], jnp.float32)
    params = base.replace(weights=w, strategy=STRATEGIES.index(strategy))
    valid = np.asarray([weights_by_name.get(n, 0.0) > 0.0
                        and n in scores_by_branch for n in MODEL_NAMES])
    n_rows = len(next(iter(scores_by_branch.values())))
    preds = np.stack(
        [np.asarray(scores_by_branch.get(name, np.zeros(n_rows)),
                    np.float32) for name in MODEL_NAMES], axis=1)
    out = combine_predictions(jnp.asarray(preds), jnp.asarray(valid),
                              params, with_confidences=False)
    return np.asarray(out["fraud_probability"])


def ensemble_decision(
    prob: jax.Array, confidence: jax.Array, confidence_threshold: float = 0.7,
    decline: float = DECLINE_THRESHOLD_DEFAULT,
    review: float = REVIEW_THRESHOLD_DEFAULT,
    monitor: float = MONITOR_THRESHOLD_DEFAULT,
) -> jax.Array:
    """Decision ladder (ensemble_predictor.py:344-356). Rungs come from
    EnsembleConfig — the reference declares them configurable and so do we
    (config.py decline/review/monitor_threshold)."""
    by_prob = jnp.where(
        prob >= decline, DECLINE,
        jnp.where(prob >= review, REVIEW,
                  jnp.where(prob >= monitor, APPROVE_WITH_MONITORING,
                            APPROVE)),
    )
    return jnp.where(confidence < confidence_threshold, REVIEW, by_prob).astype(jnp.int32)
